"""``python -m oncilla_tpu.resilience`` — chaos harness CLI.

``--smoke`` runs the canonical kill-the-owner scenario end to end,
TWICE, hardware-free, in-process:

  3-daemon local_cluster, OCM_REPLICAS=2, fast-detection config. A
  client writes half its data, then a seeded chaos schedule kills the
  owner daemon mid-workload (plus a couple of connection faults). The
  run asserts: every subsequent get() is byte-exact via the promoted
  replica, re-replication restores k=2 on a fresh rank, and — the
  determinism contract — the second run with the same seed injected the
  IDENTICAL fault interleaving (op-indexed chaos log compares equal).

``--plan`` prints the generated schedule for a seed without running
anything (what would be injected where).
"""

from __future__ import annotations

import argparse
import sys
import time

from oncilla_tpu.resilience.chaos import ChaosController, ChaosSchedule, Fault


def _scenario_schedule(seed: int, owner: int) -> ChaosSchedule:
    """Kill the owner early in the chaotic phase, with a dropped lease
    before it and a delayed one after — enough turbulence to exercise
    the retry ladder without drowning the log."""
    return ChaosSchedule.kill_at(
        seed, owner, op=4,
        extra=(
            Fault(op=2, action="drop"),
            Fault(op=7, action="delay", delay_s=0.002),
        ),
    )


def run_scenario(seed: int, verbose: bool = False) -> dict:
    """One full kill-owner-mid-workload run; returns the replay record
    (schedule + fired log + outcome) and raises on any failed check."""
    import numpy as np

    from oncilla_tpu.core.kinds import OcmKind
    from oncilla_tpu.runtime.cluster import local_cluster
    from oncilla_tpu.utils.config import OcmConfig

    cfg = OcmConfig(
        host_arena_bytes=32 << 20,
        device_arena_bytes=8 << 20,
        heartbeat_s=0.05,
        lease_s=5.0,
        replicas=2,
        detect_interval_s=0.05,
        suspect_after=1,
        dead_after=2,
        probe_timeout_s=0.25,
        dcn_stripes=2,
        dcn_stripe_min_bytes=1 << 20,
        chunk_bytes=256 << 10,
    )
    total = 4 << 20
    half = total // 2
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, total, dtype=np.uint8)
    with local_cluster(3, config=cfg) as cl:
        client = cl.client(0)
        h = client.alloc(total, OcmKind.REMOTE_HOST)
        assert h.replica_ranks, "OCM_REPLICAS=2 placement assigned no replica"
        owner = h.rank
        if verbose:
            print(f"  alloc {h.alloc_id}: primary rank {owner}, "
                  f"replicas {h.replica_ranks}")
        client.put(h, data[:half], 0)  # calm half

        schedule = _scenario_schedule(seed, owner)
        controller = ChaosController(schedule, cl.entries, kill_fn=cl.kill)
        with controller.inject():
            # Chaotic half: the kill fires at a fixed logical op index
            # while these puts (and the cluster's own background traffic)
            # drive the lease counter.
            step = 512 << 10
            for off in range(half, total, step):
                client.put(h, data[off:off + step], off)
            got = client.get(h, total)
        assert bytes(got) == data.tobytes(), (
            "get after owner kill is not byte-exact"
        )
        assert not controller.pending(), (
            f"workload too short for schedule: {controller.pending()}"
        )
        promoted = h.rank
        assert promoted != owner, "handle never failed over"

        # Re-replication restores k: the promoted primary's chain grows
        # back to 2 members, none of them the dead rank, and the fresh
        # copy is byte-exact.
        deadline = time.monotonic() + 20.0
        chain = ()
        while time.monotonic() < deadline:
            try:
                e = cl.daemons[promoted].registry.lookup(h.alloc_id)
            except Exception:  # noqa: BLE001 — registry churn mid-failover
                time.sleep(0.05)
                continue
            chain = e.chain
            if len(chain) >= 2 and owner not in chain:
                break
            time.sleep(0.05)
        assert len(chain) >= 2 and owner not in chain, (
            f"re-replication never restored k=2 (chain={chain})"
        )
        new_rep = next(r for r in chain if r != promoted)
        re = cl.daemons[new_rep].registry.lookup(h.alloc_id)
        rep_bytes = bytes(
            cl.daemons[new_rep].host_arena.view(re.extent)
        )[: re.nbytes]
        assert rep_bytes == data.tobytes(), (
            "re-replicated copy is not byte-exact"
        )
        got2 = client.get(h, total)
        assert bytes(got2) == data.tobytes()
        epoch = cl.daemons[0].epoch
        counters = dict(cl.daemons[0].res_counters)
    return {
        "seed": seed,
        "schedule": schedule,
        "log": list(controller.log),
        "owner": owner,
        "promoted": promoted,
        "chain": list(chain),
        "epoch": epoch,
        "counters": counters,
    }


def smoke(seed: int, verbose: bool = False) -> int:
    # Every run records under the flight recorder and must pass the
    # cross-rank invariant audit (obs/audit.py) — the timeline is
    # checked end to end, not just the end state. A finding raises with
    # the black-box path in the message.
    from oncilla_tpu.obs import audit as obs_audit

    print(f"resilience smoke: seed={seed} run 1/2 ...")
    with obs_audit.recorded("resilience-run1") as rec1:
        r1 = run_scenario(seed, verbose=verbose)
    print(f"  flight recorder: {rec1.summary()}")
    print(f"  owner rank {r1['owner']} killed -> promoted rank "
          f"{r1['promoted']}, chain restored to {r1['chain']}, "
          f"epoch {r1['epoch']}")
    print(f"  chaos log: {r1['log']}")
    print(f"resilience smoke: seed={seed} run 2/2 (replay) ...")
    with obs_audit.recorded("resilience-run2") as rec2:
        r2 = run_scenario(seed, verbose=verbose)
    print(f"  flight recorder: {rec2.summary()}")
    print(f"  chaos log: {r2['log']}")
    if r1["schedule"] != r2["schedule"]:
        print("resilience smoke: FAIL — schedules differ across runs")
        return 1
    if r1["log"] != r2["log"]:
        print("resilience smoke: FAIL — fault interleavings differ: "
              f"{r1['log']} vs {r2['log']}")
        return 1
    if (r1["owner"], r1["promoted"]) != (r2["owner"], r2["promoted"]):
        print("resilience smoke: FAIL — failover outcome differs")
        return 1
    print("resilience smoke: OK — kill-owner failover byte-exact, k "
          "restored, identical interleaving replayed, invariant audit "
          "clean on both timelines")
    return 0


# -- leader chaos smoke (control/): the cluster survives losing ANY rank,
# -- including the coordinator itself ------------------------------------


def _leader_cfg(**kw):
    from oncilla_tpu.utils.config import OcmConfig

    base = dict(
        host_arena_bytes=32 << 20,
        device_arena_bytes=8 << 20,
        heartbeat_s=0.05,
        lease_s=5.0,
        replicas=2,
        detect_interval_s=0.05,
        suspect_after=1,
        dead_after=2,
        probe_timeout_s=0.25,
        dcn_stripes=1,
        chunk_bytes=256 << 10,
        standby_masters=2,
        failover_wait_s=15.0,
    )
    base.update(kw)
    return OcmConfig(**base)


def _wait(pred, timeout_s: float, what: str) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _wait_state_push(cl, ranks, timeout_s: float = 10.0) -> None:
    _wait(
        lambda: all(
            cl.daemons[r]._master_state_raw is not None for r in ranks
        ),
        timeout_s, f"master-state replication to standbys {ranks}",
    )


def run_leader_kill(seed: int, verbose: bool = False) -> dict:
    """Scenario 1 — kill the LEADER mid-alloc-storm. Consistent-hash
    placement (every alloc placed at the origin, zero leader round
    trips) + k=2 chains + 2 standby masters on a 4-rank cluster: the
    storm keeps allocating while rank 0 dies, the lowest live standby
    takes the lease under a bumped epoch and resumes the dead leader's
    failover coordination, and every in-quota op reads back byte-exact.
    """
    import numpy as np

    from oncilla_tpu.core.kinds import OcmKind
    from oncilla_tpu.runtime.cluster import local_cluster

    cfg = _leader_cfg(placement="hash")
    rng = np.random.default_rng(seed)
    with local_cluster(4, config=cfg) as cl:
        client = cl.client(1)
        handles: list = []
        datas: list = []

        def storm(n: int) -> None:
            for _ in range(n):
                data = rng.integers(0, 256, 192 << 10, dtype=np.uint8)
                h = client.alloc(data.nbytes, OcmKind.REMOTE_HOST)
                client.put(h, data, 0)
                handles.append(h)
                datas.append(data)

        storm(4)  # calm phase
        _wait_state_push(cl, (1, 2))
        schedule = ChaosSchedule.kill_at(
            seed, 0, op=6,
            extra=(Fault(op=3, action="drop"),
                   Fault(op=9, action="delay", delay_s=0.002)),
        )
        controller = ChaosController(schedule, cl.entries, kill_fn=cl.kill)
        with controller.inject():
            storm(10)  # the leader dies somewhere in here
        assert not controller.pending(), (
            f"workload too short for schedule: {controller.pending()}"
        )
        _wait(lambda: cl.daemons[1].is_leader, 15.0,
              "standby rank 1 to take leadership")
        leader = cl.daemons[1]
        assert leader.epoch > 0, "election never bumped the epoch"
        # Every in-quota client op completes byte-exact.
        for h, d in zip(handles, datas):
            got = client.get(h, d.nbytes)
            assert bytes(got) == d.tobytes(), (
                f"alloc {h.alloc_id} not byte-exact after leader kill"
            )
        # The hash-placement pin: NOT ONE allocation was placed by a
        # leader — rank 0's placement counter (and everyone else's)
        # stayed at zero while every alloc journaled a hash_place.
        assert all(
            d.ldr_counters["placements"] == 0 for d in cl.daemons
        ), "REQ_ALLOC took a leader round trip under OCM_PLACEMENT=hash"
        placed = sum(
            d.ldr_counters["hash_placements"] for d in cl.daemons
        )
        assert placed >= len(handles), (
            f"{placed} hash placements for {len(handles)} allocs"
        )
        epoch = leader.epoch
        won = leader.ldr_counters["elections_won"]
    return {
        "seed": seed, "schedule": schedule, "log": list(controller.log),
        "leader": 1, "epoch": epoch, "elections_won": won,
        "allocs": len(handles),
    }


def run_leader_splitbrain(seed: int, verbose: bool = False) -> dict:
    """Scenario 2 — partition the leader from its standbys (the
    split-brain drill): rank 0 is isolated live (inbound drops,
    outbound refuses, probes fail) so it keeps BELIEVING it leads while
    rank 1 is elected under a bumped epoch. On heal the deposed leader
    learns its verdict from the PING STALE_EPOCH sentinel, fences
    itself, and answers STALE_EPOCH to coordination traffic — it never
    coordinates again, which is exactly what the flight recorder's
    leader-unique invariant certifies."""
    import numpy as np

    from oncilla_tpu.core.errors import OcmRemoteError
    from oncilla_tpu.core.kinds import OcmKind
    from oncilla_tpu.runtime import protocol as P
    from oncilla_tpu.runtime.cluster import local_cluster

    cfg = _leader_cfg(placement="leader")
    rng = np.random.default_rng(seed)
    total = 2 << 20
    data = rng.integers(0, 256, total, dtype=np.uint8)
    with local_cluster(3, config=cfg) as cl:
        client = cl.client(1)
        h = client.alloc(total, OcmKind.REMOTE_HOST)
        client.put(h, data, 0)
        _wait_state_push(cl, (1, 2))
        schedule = ChaosSchedule(
            seed=seed,
            faults=(Fault(op=4, action="isolate", rank=0),
                    Fault(op=7, action="delay", delay_s=0.002)),
        )
        controller = ChaosController(
            schedule, cl.entries,
            isolate_fn=lambda r, on: cl.daemons[r].set_partitioned(on),
        )
        step = 256 << 10
        with controller.inject():
            # Puts drive the op counter past the isolation point; the
            # ladder rides out the ownership churn retryably.
            for off in range(0, total, step):
                client.put(h, data[off:off + step], off)
            got = client.get(h, total)
        assert bytes(got) == data.tobytes()
        assert not controller.pending(), (
            f"workload too short for schedule: {controller.pending()}"
        )
        _wait(lambda: cl.daemons[1].is_leader, 15.0,
              "standby rank 1 to take leadership")
        # While partitioned, the old leader still believes it leads.
        assert cl.daemons[0].leader_rank == 0
        # Heal: the deposed leader's next probe meets the STALE_EPOCH
        # sentinel and it fences itself.
        cl.daemons[0].set_partitioned(False)
        _wait(lambda: cl.daemons[0]._fenced, 15.0,
              "the deposed leader to fence itself after the heal")
        # A fenced old leader answers STALE_EPOCH to coordination
        # traffic — it must never coordinate again.
        import socket as _socket

        e0 = cl.entries[0]
        s = _socket.create_connection((e0.connect_host, e0.port),
                                      timeout=5.0)
        try:
            for m in (
                P.Message(P.MsgType.REQ_ALLOC,
                          {"orig_rank": 1, "pid": 999, "kind": 3,
                           "nbytes": 4096}),
                P.Message(P.MsgType.ADD_NODE,
                          {"rank": 2, "host": "127.0.0.1", "port": 1,
                           "ndevices": 1, "device_arena_bytes": 1,
                           "host_arena_bytes": 1}),
            ):
                try:
                    P.request(s, m)
                except OcmRemoteError as err:
                    assert err.code == int(P.ErrCode.STALE_EPOCH), (
                        f"fenced leader answered {err.code}, not "
                        "STALE_EPOCH"
                    )
                else:
                    raise AssertionError(
                        "fenced old leader served a coordination request"
                    )
        finally:
            s.close()
        got2 = client.get(h, total)
        assert bytes(got2) == data.tobytes()
        epoch = cl.daemons[1].epoch
    return {
        "seed": seed, "schedule": schedule, "log": list(controller.log),
        "leader": 1, "epoch": epoch,
    }


def run_leader_double_kill(seed: int, verbose: bool = False) -> dict:
    """Scenario 3 — kill the leader AND an owner simultaneously: the
    two coordinated recoveries (election, then the dead owner's
    promotion + re-replication) stack. The standby leads, the surviving
    replica serves byte-exact, and k is restored among the survivors."""
    import numpy as np

    from oncilla_tpu.core.kinds import OcmKind
    from oncilla_tpu.runtime.cluster import local_cluster

    cfg = _leader_cfg(placement="leader")
    rng = np.random.default_rng(seed)
    total = 1 << 20
    with local_cluster(4, config=cfg) as cl:
        client = cl.client(1)
        # Find a victim handle whose whole chain avoids ranks 0 and 1:
        # we kill 0 (the leader) + the primary, and need the replica to
        # survive the double kill.
        victim = None
        vdata = None
        keep = []
        for _ in range(12):
            d = rng.integers(0, 256, total, dtype=np.uint8)
            h = client.alloc(total, OcmKind.REMOTE_HOST)
            client.put(h, d, 0)
            keep.append((h, d))
            if (
                h.rank in (2, 3) and h.replica_ranks
                and all(r in (2, 3) for r in h.replica_ranks)
            ):
                victim, vdata = h, d
                break
        assert victim is not None, (
            f"no chain landed wholly on ranks 2/3: "
            f"{[(h.rank, h.replica_ranks) for h, _ in keep]}"
        )
        owner = victim.rank
        _wait_state_push(cl, (1, 2))
        schedule = ChaosSchedule(
            seed=seed,
            faults=(Fault(op=3, action="kill", rank=0),
                    Fault(op=5, action="kill", rank=owner)),
        )
        controller = ChaosController(schedule, cl.entries, kill_fn=cl.kill)
        with controller.inject():
            step = 256 << 10
            for off in range(0, total, step):
                client.put(victim, vdata[off:off + step], off)
            got = client.get(victim, total)
        assert bytes(got) == vdata.tobytes()
        assert not controller.pending(), (
            f"workload too short for schedule: {controller.pending()}"
        )
        _wait(lambda: cl.daemons[1].is_leader, 15.0,
              "standby rank 1 to take leadership")
        promoted = victim.rank
        assert promoted not in (0, owner), "handle never failed over"
        # k restored among the survivors.
        deadline = time.monotonic() + 20.0
        chain = ()
        while time.monotonic() < deadline:
            try:
                e = cl.daemons[promoted].registry.lookup(victim.alloc_id)
            except Exception:  # noqa: BLE001 — registry churn mid-repair
                time.sleep(0.05)
                continue
            chain = e.chain
            if len(chain) >= 2 and owner not in chain and 0 not in chain:
                break
            time.sleep(0.05)
        assert len(chain) >= 2 and owner not in chain and 0 not in chain, (
            f"re-replication never restored k=2 (chain={chain})"
        )
        epoch = cl.daemons[1].epoch
    return {
        "seed": seed, "schedule": schedule, "log": list(controller.log),
        "leader": 1, "owner": owner, "promoted": promoted,
        "chain": list(chain), "epoch": epoch,
    }


_LEADER_SCENARIOS = (
    ("kill-leader-mid-alloc-storm", run_leader_kill),
    ("leader-splitbrain-partition", run_leader_splitbrain),
    ("kill-leader-and-owner", run_leader_double_kill),
)


def leader_smoke(seed: int, verbose: bool = False) -> int:
    """Run every leader chaos scenario TWICE under the flight recorder:
    each replay must fire the identical fault interleaving, converge to
    the same leader, and pass the full invariant audit — including the
    new leader-unique and placement-agreement checks — with zero
    findings."""
    from oncilla_tpu.obs import audit as obs_audit

    for name, fn in _LEADER_SCENARIOS:
        print(f"leader smoke [{name}]: seed={seed} run 1/2 ...")
        with obs_audit.recorded(f"leader-{name}-run1") as rec1:
            r1 = fn(seed, verbose=verbose)
        print(f"  flight recorder: {rec1.summary()}")
        print(f"  chaos log: {r1['log']}  (leader -> rank {r1['leader']},"
              f" epoch {r1['epoch']})")
        print(f"leader smoke [{name}]: seed={seed} run 2/2 (replay) ...")
        with obs_audit.recorded(f"leader-{name}-run2") as rec2:
            r2 = fn(seed, verbose=verbose)
        print(f"  flight recorder: {rec2.summary()}")
        print(f"  chaos log: {r2['log']}")
        if r1["schedule"] != r2["schedule"] or r1["log"] != r2["log"]:
            print(f"leader smoke [{name}]: FAIL — interleavings differ: "
                  f"{r1['log']} vs {r2['log']}")
            return 1
        if r1["leader"] != r2["leader"]:
            print(f"leader smoke [{name}]: FAIL — different leaders "
                  f"elected across replays")
            return 1
    print("leader smoke: OK — leader kill / split-brain partition / "
          "leader+owner double kill all converge byte-exact, replays "
          "identical, invariant audits clean (leader-unique + "
          "placement-agreement included)")
    return 0


def main(argv=None) -> int:
    from oncilla_tpu.utils.platform import honor_cpu_env

    honor_cpu_env()
    ap = argparse.ArgumentParser(
        prog="python -m oncilla_tpu.resilience",
        description="chaos/failover harness",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="run the kill-owner scenario twice and verify "
                         "byte-exact failover + deterministic replay")
    ap.add_argument("--leader-smoke", action="store_true",
                    help="run the decentralized-control-plane scenarios "
                         "(kill leader mid-alloc-storm, split-brain "
                         "partition, leader+owner double kill) twice "
                         "each with deterministic replay + invariant "
                         "audit")
    ap.add_argument("--plan", action="store_true",
                    help="print the generated random schedule for --seed")
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--nranks", type=int, default=3)
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    if args.plan:
        sched = ChaosSchedule.generate(
            args.seed, args.nranks,
            actions=("drop", "delay", "partition", "heal", "kill"),
        )
        for f in sched.faults:
            print(f"op {f.op:>4}: {f.action}"
                  + (f" rank {f.rank}" if f.rank >= 0 else "")
                  + (f" ({f.delay_s}s)" if f.action == "delay" else ""))
        return 0
    if args.smoke and args.leader_smoke:
        rc = smoke(args.seed, verbose=args.verbose)
        return rc or leader_smoke(args.seed, verbose=args.verbose)
    if args.smoke:
        return smoke(args.seed, verbose=args.verbose)
    if args.leader_smoke:
        return leader_smoke(args.seed, verbose=args.verbose)
    ap.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
