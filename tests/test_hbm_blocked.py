"""Blocked (>2 GiB) device arenas: GB-scale regions with int32 tracing.

The reference registers 2-4 GiB buffers and sweeps transfers up to 1-4 GB
over them (/root/reference/test/ocm_test.c:329-330, test/ib_client.c:85-131);
DeviceArena supports the same scale via (nblocks, 4096) blocked addressing —
no JAX_ENABLE_X64, no int64 traced offsets.
"""

import numpy as np
import pytest

from oncilla_tpu.core.hbm import _BLOCK, DeviceArena

GIB = 1 << 30
CAP = 2 * GIB + (4 << 20)  # just past the int32 cliff


@pytest.fixture(scope="module")
def big_arena():
    # ~2 GiB of host RAM on the CPU test backend; one per module.
    return DeviceArena(CAP)


def test_blocked_layout(big_arena):
    assert big_arena.buffer.shape == (CAP // _BLOCK, _BLOCK)
    assert big_arena.capacity == CAP


def test_write_read_beyond_int32(big_arena, rng):
    # An extent whose absolute offsets exceed 2**31 — the case the flat
    # int32 path cannot address.
    a = big_arena
    first = a.alloc(2 * GIB)      # pushes the next extent past the cliff
    ext = a.alloc(1 << 20)
    assert ext.offset + ext.nbytes > 2**31
    data = rng.integers(0, 256, 1 << 20, dtype=np.uint8)
    a.write(ext, data)
    np.testing.assert_array_equal(np.asarray(a.read(ext, 1 << 20)), data)
    a.free(ext)
    a.free(first)


def test_unaligned_window_write_read(big_arena, rng):
    # Byte ranges straddling block boundaries go through the window path.
    a = big_arena
    ext = a.alloc(64 << 10)
    n = 3 * _BLOCK + 513
    data = rng.integers(0, 256, n, dtype=np.uint8)
    a.write(ext, data, offset=_BLOCK - 257)   # crosses 4+ block boundaries
    got = np.asarray(a.read(ext, n, offset=_BLOCK - 257))
    np.testing.assert_array_equal(got, data)
    # Neighbouring bytes untouched.
    assert not np.any(np.asarray(a.read(ext, _BLOCK - 257, 0)))
    a.free(ext)


def test_blocked_move_aligned_and_unaligned(big_arena, rng):
    a = big_arena
    src = a.alloc(1 << 20)
    dst = a.alloc(1 << 20)
    data = rng.integers(0, 256, 1 << 20, dtype=np.uint8)
    a.write(src, data)
    a.move(src, dst, 1 << 20)                       # block-aligned rows path
    np.testing.assert_array_equal(np.asarray(a.read(dst, 1 << 20)), data)
    a.move(src, dst, 999, src_offset=17, dst_offset=33)  # window path
    np.testing.assert_array_equal(
        np.asarray(a.read(dst, 999, 33)), data[17:17 + 999]
    )
    a.free(src)
    a.free(dst)


def test_small_arena_still_flat():
    a = DeviceArena(1 << 20)
    assert a.buffer.shape == (1 << 20,)
