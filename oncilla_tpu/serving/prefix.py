"""Cross-tenant prefix-cache sharing: a content-hash radix over KV pages.

The millions-of-users win (ROADMAP item 1): identical prompt prefixes
across tenants dedup into **shared read-only refcounted extents** — one
KV page computed and stored once, attended to by every tenant whose
prompt starts the same way. The structure is a radix trie at page
granularity: each node covers exactly one page of token ids (the last
node of a published prompt may be *partial* — fewer than ``page_tokens``
tokens), children are keyed by their token chunk, and every node carries
a chain content hash (SHA-1 over the parent's hash + this node's token
bytes) so an extent's identity is the *content of the whole prefix*,
never a tenant or session id.

Sharing rules (the vLLM/Mooncake discipline on OCM pages):

- an extent's page is marked ``shared``; while ``refs > 0`` it is
  immutable (``TieredPageStore.write_page`` refuses) and unevictable
  (``_victims`` skips it);
- a tenant that must append into a *partial* shared extent copies first
  (:meth:`TieredPageStore.cow`) — copy-on-write on divergence; the
  shared original survives byte-exact for everyone else;
- ``refs == 0`` extents stay cached (retention is the point of a prefix
  cache) until :meth:`sweep` reclaims unreferenced leaves under store
  pressure.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from oncilla_tpu.core.errors import OcmError
from oncilla_tpu.obs import journal as obs_journal
from oncilla_tpu.serving.metrics import ServingStats
from oncilla_tpu.serving.tiers import Page, TieredPageStore
from oncilla_tpu.utils.debug import printd


def _chain_hash(parent_key: str, tokens: tuple[int, ...]) -> str:
    h = hashlib.sha1(parent_key.encode("ascii"))
    h.update(b"\x00".join(str(t).encode("ascii") for t in tokens))
    return h.hexdigest()


@dataclass
class SharedExtent:
    """One radix node: a page of KV for one page of prefix tokens."""

    key: str
    tokens: tuple[int, ...]
    page: Page
    parent: "SharedExtent | None" = None
    children: dict = field(default_factory=dict)   # full-page nodes
    partials: dict = field(default_factory=dict)   # partial-tail nodes

    @property
    def fill(self) -> int:
        return len(self.tokens)

    @property
    def refs(self) -> int:
        return self.page.refs


class PrefixCache:
    """The page-granular radix trie over one :class:`TieredPageStore`."""

    def __init__(self, store: TieredPageStore, page_tokens: int,
                 stats: ServingStats | None = None):
        self.store = store
        self.page_tokens = int(page_tokens)
        self.stats = stats or store.stats
        self._root = SharedExtent(key="", tokens=(), page=None)  # sentinel

    # -- lookup -----------------------------------------------------------

    def match(self, tokens) -> tuple[list[SharedExtent], int]:
        """Longest shared prefix of ``tokens``: full-page extents chunk
        by chunk, then (when what remains is a short tail) an exact
        partial extent. Returns (extents, tokens_matched); the caller
        must :meth:`acquire` before using any page."""
        toks = tuple(int(t) for t in tokens)
        node = self._root
        matched: list[SharedExtent] = []
        i = 0
        P = self.page_tokens
        while i + P <= len(toks):
            child = node.children.get(toks[i:i + P])
            if child is None:
                break
            matched.append(child)
            node = child
            i += P
        rest = toks[i:]
        if 0 < len(rest) < P:
            part = node.partials.get(rest)
            if part is not None:
                matched.append(part)
                i += len(rest)
        return matched, i

    def child(self, parent: SharedExtent | None, tokens) -> SharedExtent | None:
        """The single extent extending ``parent`` by exactly ``tokens``
        (full-page or partial by length) — the incremental form of
        :meth:`match`, what the engine probes at every page boundary so
        prompts arriving *simultaneously* still dedup: session B adopts
        the page session A published one turn earlier."""
        node = parent or self._root
        toks = tuple(int(t) for t in tokens)
        table = (node.children if len(toks) == self.page_tokens
                 else node.partials)
        return table.get(toks)

    # -- publication ------------------------------------------------------

    def publish(self, parent: SharedExtent | None, tokens, page: Page
                ) -> SharedExtent:
        """Publish ``page`` as the KV for ``tokens`` extending
        ``parent`` (None = the prompt's first page). Content-hash
        dedup: when the chain already carries this exact extent —
        another tenant prefilled the same prefix first — the fresh page
        is returned to the store and the existing extent wins, so the
        cache can never hold two copies of one prefix."""
        node = parent or self._root
        toks = tuple(int(t) for t in tokens)
        if not 0 < len(toks) <= self.page_tokens:
            raise ValueError(f"extent of {len(toks)} tokens "
                             f"(page is {self.page_tokens})")
        table = (node.children if len(toks) == self.page_tokens
                 else node.partials)
        existing = table.get(toks)
        if existing is not None:
            if page is not existing.page:
                self.store.free_page(page)
            return existing
        page.shared = True
        ext = SharedExtent(
            key=_chain_hash(node.key, toks), tokens=toks, page=page,
            parent=None if node is self._root else node,
        )
        table[toks] = ext
        self.stats.note_extents(+1)
        obs_journal.record("prefix_publish", key=ext.key[:12],
                           tokens=len(toks), nbytes=page.nbytes,
                           partial=len(toks) < self.page_tokens)
        return ext

    # -- refcounts --------------------------------------------------------

    def acquire(self, ext: SharedExtent) -> None:
        ext.page.refs += 1
        self.stats.note_prefix_hit(ext.page.nbytes)
        obs_journal.record("prefix_hit", key=ext.key[:12],
                           refs=ext.page.refs, nbytes=ext.page.nbytes)

    def release(self, ext: SharedExtent) -> None:
        if ext.page.refs <= 0:
            raise ValueError(f"release of unreferenced extent {ext.key[:12]}")
        ext.page.refs -= 1
        self.stats.note_prefix_release(ext.page.nbytes)

    # -- retention --------------------------------------------------------

    def _walk(self, node: SharedExtent):
        for table in (node.children, node.partials):
            for ext in table.values():
                yield ext
                yield from self._walk(ext)

    def extents(self) -> list[SharedExtent]:
        return list(self._walk(self._root))

    def shared_bytes(self) -> int:
        """Bytes deduplicated: each extra reference beyond the first is
        a page some tenant did NOT have to store privately."""
        return sum(max(e.page.refs - 1, 0) * e.page.nbytes
                   for e in self.extents())

    # -- persistence (FROZEN tier, ROADMAP item 5) ------------------------

    def persist(self, frozen) -> int:
        """Write every extent's page bytes + trie position into a
        :class:`~oncilla_tpu.persist.FrozenStore` (``prefix-<chainhash>``
        keys). Parent-first (:meth:`_walk` order) so a restored store is
        always a valid trie prefix even if the write is cut short.
        Returns the number of extents persisted."""
        n = 0
        live = {f"prefix-{ext.key}" for ext in self.extents()}
        for fkey in frozen.keys():
            # The store is an exact manifest of the trie: a chain swept
            # since the last persist must not resurrect at restore.
            if fkey.startswith("prefix-") and fkey not in live:
                frozen.delete(fkey)
        for ext in self.extents():
            data = self.store.read_page(ext.page)
            frozen.write(
                f"prefix-{ext.key}",
                data.tobytes(),
                meta={
                    "kind": "prefix",
                    "key": ext.key,
                    "tokens": list(ext.tokens),
                    "parent": ext.parent.key if ext.parent else "",
                    "nbytes": int(ext.page.nbytes),
                },
            )
            n += 1
        obs_journal.record("prefix_persist", extents=n)
        return n

    def restore(self, frozen) -> int:
        """Re-publish persisted extents from ``frozen`` into the trie —
        the warm-boot leg. Parents restore before children (chain-hash
        identity demands it); a chain with a missing or corrupt ancestor
        is dropped WHOLE below the break (a child must never publish over
        a hole — its chain hash would lie about the bytes beneath it).
        Returns the number of extents re-published."""
        import numpy as np

        recs: dict[str, tuple[str, dict]] = {}
        for fkey in frozen.keys():
            if not fkey.startswith("prefix-"):
                continue
            meta = frozen.meta(fkey)
            if meta.get("kind") == "prefix":
                recs[meta["key"]] = (fkey, meta)

        def depth(key: str) -> int | None:
            d = 0
            while key:
                rec = recs.get(key)
                if rec is None:
                    return None  # broken ancestry: skip the whole chain
                key = rec[1]["parent"]
                d += 1
            return d

        published: dict[str, SharedExtent | None] = {"": None}
        n = 0
        order = sorted(
            (k for k in recs if depth(k) is not None),
            key=lambda k: depth(k),
        )
        for key in order:
            fkey, meta = recs[key]
            parent_key = meta["parent"]
            if parent_key not in published:
                continue  # parent refused at read time below
            try:
                data = frozen.read_bytes(fkey)
            except OcmError:
                # Typed refusal (corrupt entry quarantined by the store):
                # this chain ends here — descendants stay unpublished.
                printd("prefix restore: dropping chain at %s "
                       "(frozen entry refused)", fkey)
                continue
            page = self.store.alloc_page(
                np.frombuffer(data, dtype=np.uint8), shared=True
            )
            ext = self.publish(
                published[parent_key], tuple(meta["tokens"]), page
            )
            published[key] = ext
            n += 1
        obs_journal.record("prefix_restore", extents=n,
                           persisted=len(recs))
        return n

    def sweep(self) -> int:
        """Reclaim unreferenced LEAF extents (children first — an inner
        node's page may still back a referenced chain below it).
        Returns the number of pages freed."""
        freed = 0
        changed = True
        while changed:
            changed = False
            for node in [self._root, *self.extents()]:
                for table in (node.children, node.partials):
                    for toks, ext in list(table.items()):
                        if (ext.page.refs == 0 and not ext.children
                                and not ext.partials):
                            del table[toks]
                            ext.page.shared = False
                            self.store.free_page(ext.page)
                            self.stats.note_extents(-1)
                            freed += 1
                            changed = True
        return freed
