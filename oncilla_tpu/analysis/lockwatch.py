"""Runtime lock-order watchdog (``OCM_LOCKWATCH=1``).

The control plane's deadlock history is ordering, not atomicity: the
reference wedged when a per-peer connection mutex was held across a
request/reply round-trip (see runtime/pool.py's module docstring). Static
lint catches the lexical shape; this watchdog catches the dynamic one — it
records which locks are held when another is acquired, aggregates the
edges into a site-level acquisition-order graph, and reports cycles
(potential deadlocks) plus over-threshold hold times.

Usage: runtime modules create locks through :func:`make_lock` with a
stable *site name* (e.g. ``"daemon._conns_mu"``). Disabled (the default),
that returns a plain ``threading.Lock`` — zero overhead. With
``OCM_LOCKWATCH=1`` it returns a :class:`WatchedLock` recording into the
module-global :class:`LockGraph`. Tests then assert
``lockwatch.cycles() == []``.

Design notes:

- Edges are keyed by site name, not lock instance: every daemon's
  ``_conns_mu`` is the same node, so ordering discipline is checked
  across the whole cluster in one graph.
- Only *blocking* acquires record edges. A ``acquire(blocking=False)``
  probe cannot deadlock, and the pool's lease fast path (try-acquire of
  an entry lock while holding the pool condition) would otherwise report
  a by-construction-safe cycle.
- Hold times over ``OCM_LOCKWATCH_HOLD_MS`` (default 250 ms) are recorded
  with the site name; a long hold is not an error by itself but is the
  precondition for every convoy the stress tests chase.
"""

from __future__ import annotations

import os
import threading
import time

__all__ = [
    "enabled", "make_lock", "make_rlock", "cycles", "assert_acyclic",
    "snapshot", "reset", "WatchedLock", "LockGraph",
]


def enabled() -> bool:
    # OCM_WAITWATCH implies lock instrumentation: the unified wait-for
    # graph (analysis/waitwatch.py) fuses pool slots and RPC edges into
    # this module's GRAPH, and those edges are only meaningful if lock
    # holds land on the same per-thread stack.
    env = os.environ
    return (env.get("OCM_LOCKWATCH", "") not in ("", "0")
            or env.get("OCM_WAITWATCH", "") not in ("", "0"))


def _hold_threshold_s() -> float:
    try:
        return float(os.environ.get("OCM_LOCKWATCH_HOLD_MS", "250")) / 1e3
    except ValueError:
        return 0.25


class LockGraph:
    """Aggregated acquisition-order graph; thread-safe, process-global."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # held-site -> {acquired-site -> count}
        self.edges: dict[str, dict[str, int]] = {}
        self.acquires: dict[str, int] = {}
        # (site, seconds) for holds over the threshold, bounded.
        self.long_holds: list[tuple[str, float]] = []
        self._tls = threading.local()

    # -- recording (called from WatchedLock) ----------------------------

    def _held_stack(self) -> list[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def note_acquire_attempt(self, site: str) -> None:
        held = self._held_stack()
        if not held:
            return
        with self._mu:
            for h in held:
                if h != site:
                    d = self.edges.setdefault(h, {})
                    d[site] = d.get(site, 0) + 1

    def note_acquired(self, site: str) -> None:
        self._held_stack().append(site)
        with self._mu:
            self.acquires[site] = self.acquires.get(site, 0) + 1

    def note_released(self, site: str, held_s: float) -> None:
        held = self._held_stack()
        # Remove the most recent entry for this site (locks are usually,
        # but not necessarily, released LIFO).
        for i in range(len(held) - 1, -1, -1):
            if held[i] == site:
                del held[i]
                break
        if held_s >= _hold_threshold_s():
            with self._mu:
                if len(self.long_holds) < 1024:
                    self.long_holds.append((site, held_s))

    # -- reporting ------------------------------------------------------

    def cycles(self) -> list[list[str]]:
        """Elementary cycles in the site graph (DFS; the graph is tiny).
        A cycle means two code paths acquire the same locks in opposite
        orders — a potential deadlock even if this run got lucky."""
        with self._mu:
            adj = {k: sorted(v) for k, v in self.edges.items()}
        out: list[list[str]] = []
        seen_cycles: set[tuple[str, ...]] = set()
        nodes = sorted(set(adj) | {n for vs in adj.values() for n in vs})
        for start in nodes:
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in adj.get(node, []):
                    if nxt == start:
                        cyc = path[:]
                        # Canonicalize rotation so A->B->A == B->A->B.
                        i = cyc.index(min(cyc))
                        key = tuple(cyc[i:] + cyc[:i])
                        if key not in seen_cycles:
                            seen_cycles.add(key)
                            out.append(list(key) + [key[0]])
                    elif nxt not in path and len(path) < 16:
                        stack.append((nxt, path + [nxt]))
        return out

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "edges": {k: dict(v) for k, v in self.edges.items()},
                "acquires": dict(self.acquires),
                "long_holds": list(self.long_holds),
            }

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()
            self.acquires.clear()
            self.long_holds.clear()


GRAPH = LockGraph()


class WatchedLock:
    """``threading.Lock``-shaped wrapper that records into :data:`GRAPH`.
    Also works as the lock of a ``threading.Condition`` — the Condition's
    wait() releases through :meth:`release` and re-acquires through
    :meth:`acquire`, so wait-windows drop out of the held stack exactly
    like the real lock does."""

    def __init__(self, site: str, inner=None):
        self.site = site
        self._inner = inner if inner is not None else threading.Lock()
        self._t0 = 0.0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            GRAPH.note_acquire_attempt(self.site)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            GRAPH.note_acquired(self.site)
            self._t0 = time.perf_counter()
        return ok

    def release(self) -> None:
        held_s = time.perf_counter() - self._t0
        self._inner.release()
        GRAPH.note_released(self.site, held_s)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "WatchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"WatchedLock({self.site!r}, {self._inner!r})"


def make_lock(site: str) -> threading.Lock | WatchedLock:
    """A lock for ``site`` (stable dotted name, e.g. ``"pool._lock"``).
    Plain ``threading.Lock`` unless ``OCM_LOCKWATCH=1``."""
    if not enabled():
        return threading.Lock()
    return WatchedLock(site)


def make_rlock(site: str) -> threading.RLock | WatchedLock:
    if not enabled():
        return threading.RLock()
    return WatchedLock(site, inner=threading.RLock())


def cycles() -> list[list[str]]:
    return GRAPH.cycles()


def assert_acyclic() -> None:
    cyc = GRAPH.cycles()
    if cyc:
        pretty = "; ".join(" -> ".join(c) for c in cyc)
        raise AssertionError(f"lock-order cycles detected: {pretty}")


def snapshot() -> dict:
    return GRAPH.snapshot()


def reset() -> None:
    GRAPH.reset()
