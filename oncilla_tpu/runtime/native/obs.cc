#include "obs.hh"

#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <algorithm>
#include <array>
#include <atomic>
#include <chrono>
#include <cinttypes>
#include <cstdlib>
#include <cstring>
#include <random>

namespace ocm {

uint32_t crc32_update(uint32_t crc, const uint8_t* p, size_t n) {
  static const auto table = [] {
    std::array<uint32_t, 256> t{};
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
    return t;
  }();
  crc ^= 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i)
    crc = table[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

namespace obs {
namespace {

// mkdir -p for the flight-recorder directory (OCM_FLIGHTREC may name a
// nested path that nothing created yet; flightrec.py does makedirs).
void mkdirs(const std::string& path) {
  std::string cur;
  for (size_t i = 0; i <= path.size(); ++i) {
    if (i == path.size() || path[i] == '/') {
      if (!cur.empty()) ::mkdir(cur.c_str(), 0777);
      if (i < path.size()) cur += '/';
      continue;
    }
    cur += path[i];
  }
}

std::string env_str(const char* name) {
  const char* v = getenv(name);
  return v ? std::string(v) : std::string();
}

std::atomic<int> g_tid_counter{0};
thread_local int t_tid = 0;
thread_local std::string t_thread_name;

int this_tid() {
  if (t_tid == 0) t_tid = ++g_tid_counter;
  return t_tid;
}

}  // namespace

std::string json_escape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += char(c);
        }
    }
  }
  return out;
}

void Fields::key(const char* k) {
  if (!buf_.empty()) buf_ += ',';
  buf_ += '"';
  buf_ += k;
  buf_ += "\":";
}

Fields& Fields::i(const char* k, int64_t v) {
  key(k);
  char buf[32];
  std::snprintf(buf, sizeof(buf), "%" PRId64, v);
  buf_ += buf;
  return *this;
}

Fields& Fields::u(const char* k, uint64_t v) {
  key(k);
  char buf[32];
  std::snprintf(buf, sizeof(buf), "%" PRIu64, v);
  buf_ += buf;
  return *this;
}

Fields& Fields::d(const char* k, double v) {
  key(k);
  char buf[48];
  std::snprintf(buf, sizeof(buf), "%.6f", v);
  buf_ += buf;
  return *this;
}

Fields& Fields::s(const char* k, const std::string& v) {
  key(k);
  buf_ += '"';
  buf_ += json_escape(v);
  buf_ += '"';
  return *this;
}

Fields& Fields::b(const char* k, bool v) {
  key(k);
  buf_ += v ? "true" : "false";
  return *this;
}

double wall_s() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

double mono_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void set_thread_name(const std::string& name) { t_thread_name = name; }

uint64_t rand_id() {
  static std::mutex mu;
  static std::mt19937_64 rng(std::random_device{}() ^
                             uint64_t(::getpid()) << 32 ^
                             uint64_t(std::chrono::steady_clock::now()
                                          .time_since_epoch()
                                          .count()));
  std::lock_guard<std::mutex> g(mu);
  uint64_t v = rng();
  return v ? v : 1;  // 0 means "absent" on the wire
}

// -- FlightRec ----------------------------------------------------------

FlightRec::FlightRec(const std::string& jid) : jid_(jid) {
  dir_ = env_str("OCM_FLIGHTREC");
  std::string sb = env_str("OCM_FLIGHTREC_SEG_BYTES");
  if (!sb.empty()) {
    long v = std::atol(sb.c_str());
    if (v > 0) seg_bytes_ = size_t(v);
  }
  std::string ms = env_str("OCM_FLIGHTREC_MAX_SEGS");
  if (!ms.empty()) {
    long v = std::atol(ms.c_str());
    if (v > 0) max_segs_ = size_t(v);
  }
}

FILE* FlightRec::open_segment_locked(const std::string& label) {
  ++seg_seq_;
  char name[256];
  if (label.empty()) {
    std::snprintf(name, sizeof(name), "fr-%s-%05d.seg", jid_.c_str(),
                  seg_seq_);
  } else {
    std::snprintf(name, sizeof(name), "fr-%s-%s-%05d.seg", jid_.c_str(),
                  label.c_str(), seg_seq_);
  }
  mkdirs(dir_);
  std::string path = dir_ + "/" + name;
  FILE* fh = std::fopen(path.c_str(), "wb");
  if (fh == nullptr) return nullptr;
  static const uint8_t hdr[5] = {'O', 'C', 'M', 'J', 1};
  if (std::fwrite(hdr, 1, sizeof(hdr), fh) != sizeof(hdr)) {
    std::fclose(fh);
    return nullptr;
  }
  own_segs_.push_back(path);
  rotate_locked();
  return fh;
}

void FlightRec::rotate_locked() {
  // OCM_FLIGHTREC_MAX_SEGS bounds THIS writer's on-disk footprint (a
  // long soak used to grow the directory without bound): oldest own
  // segment goes first, other processes' evidence is never touched.
  if (max_segs_ == 0) return;
  while (own_segs_.size() > max_segs_) {
    ::unlink(own_segs_.front().c_str());
    own_segs_.pop_front();
  }
}

void FlightRec::append(const std::string& payload) {
  if (dir_.empty()) return;
  uint8_t frame[8];
  uint32_t len = uint32_t(payload.size());
  uint32_t crc = crc32_update(
      0, reinterpret_cast<const uint8_t*>(payload.data()), payload.size());
  for (int i = 0; i < 4; ++i) frame[i] = (len >> (8 * i)) & 0xff;
  for (int i = 0; i < 4; ++i) frame[4 + i] = (crc >> (8 * i)) & 0xff;
  std::lock_guard<std::mutex> g(mu_);
  if (failures_ >= 8) return;  // disarmed: a full disk must not wedge
  if (fh_ == nullptr) {
    fh_ = open_segment_locked("");
    if (fh_ == nullptr) {
      ++failures_;
      return;
    }
    written_ = 5;
  }
  bool ok = std::fwrite(frame, 1, sizeof(frame), fh_) == sizeof(frame) &&
            std::fwrite(payload.data(), 1, payload.size(), fh_) ==
                payload.size() &&
            std::fflush(fh_) == 0;
  if (!ok) {
    ++failures_;
    std::fclose(fh_);
    fh_ = nullptr;
    return;
  }
  failures_ = 0;
  written_ += sizeof(frame) + payload.size();
  if (written_ >= seg_bytes_) {
    std::fclose(fh_);
    fh_ = nullptr;
  }
}

void FlightRec::dump(const std::vector<std::string>& payloads,
                     const std::string& label) {
  if (dir_.empty() || payloads.empty()) return;
  std::lock_guard<std::mutex> g(mu_);
  FILE* fh = open_segment_locked(label);
  if (fh == nullptr) return;
  for (const std::string& p : payloads) {
    uint8_t frame[8];
    uint32_t len = uint32_t(p.size());
    uint32_t crc = crc32_update(
        0, reinterpret_cast<const uint8_t*>(p.data()), p.size());
    for (int i = 0; i < 4; ++i) frame[i] = (len >> (8 * i)) & 0xff;
    for (int i = 0; i < 4; ++i) frame[4 + i] = (crc >> (8 * i)) & 0xff;
    if (std::fwrite(frame, 1, sizeof(frame), fh) != sizeof(frame) ||
        std::fwrite(p.data(), 1, p.size(), fh) != p.size())
      break;
  }
  std::fflush(fh);
  ::fsync(fileno(fh));
  std::fclose(fh);
}

void FlightRec::flush() {
  std::lock_guard<std::mutex> g(mu_);
  if (fh_ != nullptr) {
    std::fflush(fh_);
    ::fsync(fileno(fh_));
  }
}

// -- Journal ------------------------------------------------------------

namespace {

std::string make_jid() {
  char buf[64];
  std::snprintf(buf, sizeof(buf), "%x-%08x", unsigned(::getpid()),
                unsigned(rand_id() & 0xffffffffu));
  return buf;
}

}  // namespace

Journal::Journal() : jid_(make_jid()), flightrec_(jid_) {
  // OCM_FLIGHTREC alone is a complete opt-in (journal.py): a flight
  // recorder that also required OCM_EVENTS=1 would record nothing.
  std::string ev = env_str("OCM_EVENTS");
  enabled_ = (!ev.empty() && ev != "0") || flightrec_.configured();
  std::string cap = env_str("OCM_EVENTS_CAP");
  if (!cap.empty()) {
    long v = std::atol(cap.c_str());
    if (v > 0) cap_ = size_t(v);
  }
}

void Journal::record(const char* ev, const std::string& track,
                     const std::string& extra) {
  if (!enabled_) return;
  std::string thread =
      t_thread_name.empty() ? std::string("native") : t_thread_name;
  Fields head;
  head.s("ev", ev).d("ts", wall_s()).d("mono", mono_s());
  head.i("pid", int64_t(::getpid())).i("tid", this_tid()).s("thread", thread);
  std::string rec;
  {
    std::lock_guard<std::mutex> g(mu_);
    ++seq_;
    Fields tail;
    tail.s("track", track).s("jid", jid_).u("seq", seq_);
    rec = "{" + head.str() + (extra.empty() ? "" : "," + extra) + "," +
          tail.str() + "}";
    ring_.push_back(rec);
    while (ring_.size() > cap_) ring_.pop_front();
  }
  // Spill OUTSIDE the ring lock (journal.py discipline): the recorder
  // has its own lock, and a slow disk must never serialize hot-path
  // record() callers behind the ring.
  flightrec_.append(rec);
}

size_t Journal::size() {
  std::lock_guard<std::mutex> g(mu_);
  return ring_.size();
}

std::string Journal::dump_jsonl() {
  std::lock_guard<std::mutex> g(mu_);
  std::string out;
  for (const std::string& r : ring_) {
    out += r;
    out += '\n';
  }
  return out;
}

void Journal::spill_ring(const std::string& label) {
  if (!flightrec_.configured()) return;
  std::vector<std::string> evts;
  {
    std::lock_guard<std::mutex> g(mu_);
    evts.assign(ring_.begin(), ring_.end());
  }
  flightrec_.dump(evts, label);
}

// -- OpStatsBook --------------------------------------------------------

void OpStatsBook::note(const std::string& op, double dt_s,
                       uint64_t nbytes) {
  std::lock_guard<std::mutex> g(mu_);
  Rec& r = stats_[op];
  r.count += 1;
  r.total_s += dt_s;
  r.total_bytes += nbytes;
  r.samples.push_back(dt_s);
  while (r.samples.size() > 2048) r.samples.pop_front();
}

std::map<std::string, OpSnap> OpStatsBook::snapshot() const {
  std::lock_guard<std::mutex> g(mu_);
  std::map<std::string, OpSnap> out;
  for (const auto& kv : stats_) {
    OpSnap s;
    s.count = kv.second.count;
    s.total_s = kv.second.total_s;
    s.total_bytes = kv.second.total_bytes;
    if (!kv.second.samples.empty()) {
      std::vector<double> sorted(kv.second.samples.begin(),
                                 kv.second.samples.end());
      std::sort(sorted.begin(), sorted.end());
      s.p50_s = sorted[sorted.size() / 2];
      size_t i99 = std::min(size_t(double(sorted.size()) * 0.99),
                            sorted.size() - 1);
      s.p99_s = sorted[i99];
    }
    out[kv.first] = s;
  }
  return out;
}

// -- PromDoc ------------------------------------------------------------

std::string prom_num(double v) {
  if (v == int64_t(v) && v >= -9.2e18 && v <= 9.2e18) {
    char buf[32];
    std::snprintf(buf, sizeof(buf), "%" PRId64, int64_t(v));
    return buf;
  }
  char buf[48];
  std::snprintf(buf, sizeof(buf), "%.12g", v);
  return buf;
}

namespace {

std::string label_escape(const std::string& s) {
  std::string out;
  for (char c : s) {
    if (c == '\\') out += "\\\\";
    else if (c == '"') out += "\\\"";
    else if (c == '\n') out += "\\n";
    else out += c;
  }
  return out;
}

}  // namespace

void PromDoc::sample(const std::string& family, const char* kind,
                     const char* help, double value, const Labels& labels) {
  auto it = fams_.find(family);
  if (it == fams_.end()) {
    order_.push_back(family);
    it = fams_.emplace(family, Fam{kind, help, {}}).first;
  }
  std::string line = family + "{";
  bool first = true;
  for (const auto& kv : labels) {
    if (!first) line += ',';
    first = false;
    line += kv.first + "=\"" + label_escape(kv.second) + "\"";
  }
  line += "} " + prom_num(value);
  it->second.samples.push_back(line);
}

std::string PromDoc::text() const {
  std::string out;
  for (const std::string& family : order_) {
    const Fam& f = fams_.at(family);
    out += "# HELP " + family + " " + f.help + "\n";
    out += "# TYPE " + family + " " + f.kind + "\n";
    for (const std::string& s : f.samples) out += s + "\n";
  }
  return out;
}

}  // namespace obs
}  // namespace ocm
