"""Train the same tiny models four ways — one script, every parallelism
axis the framework supports.

1. dense  (dp, tp, sp): GSPMD shardings + ring attention over sp
2. moe    (dp, ep, tp): expert-parallel all-to-all dispatch
3. gpipe  (dp, pp):     dense layers through the pipeline executor
4. moe-pp (dp, pp):     MoE layers through the pipeline (aux channel)

Run (from the repo root; CPU is fine — 8 virtual devices are forced):
      JAX_PLATFORMS=cpu python examples/train_parallel.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    from oncilla_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(8)

from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from oncilla_tpu.models import train  # noqa: E402
from oncilla_tpu.models.llama import LlamaConfig  # noqa: E402
from oncilla_tpu.models.moe import MoeConfig  # noqa: E402


def run(name, mesh, make_state, make_step, cfg, batch, seq, steps=4):
    rng = np.random.default_rng(0)
    params, opt_state, tx = make_state(jax.random.key(0), cfg, mesh, lr=5e-3)
    step = make_step(cfg, mesh, tx)
    tokens = jax.device_put(
        train.sample_batch(rng, cfg, batch, seq),
        NamedSharding(mesh, P("dp", None) if "sp" not in mesh.axis_names
                      else train.data_spec()),
    )
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    print(f"  {name:8s} mesh={dict(mesh.shape)} "
          f"loss {losses[0]:.3f} -> {losses[-1]:.3f}")


if __name__ == "__main__":
    n = len(jax.devices())
    print(f"== training across {n} devices ==")
    import dataclasses

    dense = LlamaConfig.tiny()
    moe = MoeConfig.tiny()
    pp_dense = dataclasses.replace(dense, n_layers=4)

    run("dense", train.make_mesh(n), train.make_train_state,
        train.make_train_step, dense, batch=4, seq=32)
    run("moe", train.make_moe_mesh(n), train.make_moe_train_state,
        train.make_moe_train_step, moe, batch=4, seq=32)
    run("gpipe", train.make_pp_mesh(n, n_layers=4), train.make_pp_train_state,
        train.make_pp_train_step, pp_dense, batch=8, seq=32)
    run("moe-pp", train.make_pp_mesh(n, n_layers=moe.n_layers),
        train.make_moe_pp_train_state, train.make_moe_pp_train_step,
        moe, batch=8, seq=32)
    print("all four parallelism modes trained")
