"""Control-plane wire protocol.

The reference ships raw fixed-size C structs over TCP with no versioning or
endianness handling (``send_recv_msg``, /root/reference/src/mem.c:63-88), a
homogeneous-architecture assumption SURVEY.md flags as a bug to replace. This
module defines a versioned, explicitly little-endian framed protocol spoken
identically by the Python client/daemon and the C++ daemon
(oncilla_tpu/runtime/native/daemon.cc).

Frame:  magic "OCM1" (4 B) | version u8 | type u8 | flags u16 | payload_len u32
Payload: type-specific packed fields, strings length-prefixed (u16 + utf-8),
raw data carried after the fixed fields (DATA_PUT / DATA_GET_OK).

Message set mirrors /root/reference/inc/msg.h:24-45 (CONNECT, ADD_NODE,
REQ_ALLOC, DO_ALLOC, REQ_FREE, DO_FREE, RELEASE_APP) plus the capability
upgrades: DATA_PUT/DATA_GET (the DCN data plane), HEARTBEAT (leases — the
reference's unresolved liveness TODO, main.c:6-7), and STATUS for
observability.
"""

from __future__ import annotations

import enum
import socket
import struct
from dataclasses import dataclass, field

from oncilla_tpu.core.errors import OcmProtocolError, OcmRemoteError

MAGIC = b"OCM1"
VERSION = 2  # v2: owners field on DISCONNECT/HEARTBEAT, RECLAIM_APP
HEADER = struct.Struct("<4sBBHI")  # magic, version, type, flags, payload_len
MAX_PAYLOAD = 64 << 20  # sanity cap; large transfers are chunked above this

# Header-flag bits (the u16 the v2 frame always carried but never used).
# Capabilities ride the SAME frame format, so a v2 peer that ignores
# flags interoperates unmodified: it simply never grants a capability.
# The native C++ daemon serves the DATA-plane subset (it echoes
# FLAG_CAP_COALESCE and lands FLAG_MORE bursts zero-copy) and declines
# every other bit by silence — its grant mask is protocol.hh
# kCapsImplemented, pinned by the declined-by-silence tests.
#
# FLAG_MORE on DATA_PUT marks a non-final chunk of a coalesced burst: the
# daemon applies the chunk but defers its reply, answering ONCE — at the
# first chunk without the bit — with a DATA_PUT_OK covering the whole
# burst (or the burst's first ERROR). Senders may only set it after the
# peer granted FLAG_CAP_COALESCE.
FLAG_MORE = 0x0001
# FLAG_CAP_COALESCE on CONNECT offers ACK coalescing; a daemon that
# implements it (the Python daemon AND the native C++ daemon) echoes the
# bit on CONNECT_CONFIRM. A flags=0 reply (old v2 Python daemon)
# declines, and the sender stays on the lockstep one-reply-per-chunk
# protocol.
FLAG_CAP_COALESCE = 0x0002
# FLAG_CAP_TRACE on CONNECT offers distributed-trace propagation (the
# same offer/echo dance as FLAG_CAP_COALESCE). Only after the peer
# echoes it may a sender set FLAG_TRACE_CTX on requests; a flags=0 reply
# (un-upgraded v2 daemon, native C++ daemon) declines by silence and the
# sender ships plain frames — interop untouched.
FLAG_CAP_TRACE = 0x0004
# FLAG_TRACE_CTX on a request: the first 16 bytes of the data tail are a
# trace context (obs/trace.py: trace_id u64 | span_id u64), NOT payload.
# Receivers strip the prefix before dispatch and attach the context to
# their serve-side spans / forwarded hops. Replies never carry it (the
# requester already owns the context).
FLAG_TRACE_CTX = 0x0008
# FLAG_CAP_REPLICA on CONNECT offers k-way replicated allocations
# (resilience/): same offer/echo dance as FLAG_CAP_COALESCE. Only after
# the daemon echoes it may a client set FLAG_REPLICAS on REQ_ALLOC; a
# flags=0 reply (un-upgraded v2 daemon, native C++ daemon) declines by
# silence and every allocation stays single-copy — with OCM_REPLICAS
# unset/1 the bit is never even offered, so the wire is byte-for-byte
# the pre-replication protocol.
FLAG_CAP_REPLICA = 0x0010
# FLAG_REPLICAS on REQ_ALLOC: the data tail carries one u8 — the
# requested copy count k (after any trace prefix is stripped). The fixed
# schema stays untouched so un-flagged frames remain byte-identical and
# parseable by every v2 peer; chain membership itself rides the new
# DO_REPLICA message, never a legacy type.
FLAG_REPLICAS = 0x0020
# FLAG_FANOUT on DATA_PUT marks a primary->replica replication leg (and
# re-replication streaming). Replica holders accept ONLY fan-out writes
# while they believe their primary alive — a client write landing on a
# replica is rejected NOT_PRIMARY so the copies can never diverge — and
# never re-fan a fan-out write (no forwarding loops).
FLAG_FANOUT = 0x0040
# FLAG_CAP_QOS on CONNECT offers multi-tenant QoS (qos/): per-app
# quota/priority declaration and the priority tails on the alloc chain.
# Same offer/echo dance as the other capabilities: a flags=0 reply
# (un-upgraded v2 daemon, native C++ daemon) declines by silence and the
# app runs at the server-side defaults. With OCM_QUOTA_*/OCM_PRIORITY
# unset the bit is never offered, so the wire stays byte-for-byte the
# pre-QoS protocol.
FLAG_CAP_QOS = 0x0080
# FLAG_QOS_TAIL marks a QoS data tail (after any trace prefix is
# stripped): on CONNECT, the app's declared profile
# (priority u8 | quota_bytes u64 | quota_handles u32, qos/policy.py
# PROFILE_TAIL); on REQ_ALLOC / DO_ALLOC / DO_REPLICA, one u8 — the
# allocation's priority class, appended AFTER the FLAG_REPLICAS u8 when
# both ride. Only ever set toward a peer that granted FLAG_CAP_QOS;
# the fixed schemas stay untouched so un-flagged frames remain
# byte-identical and parseable by every v2 peer.
FLAG_QOS_TAIL = 0x0100
# FLAG_CAP_FABRIC on CONNECT offers data-fabric negotiation (fabric/):
# the client asks which one-sided fabrics the daemon serves besides the
# framed-TCP engine this protocol itself rides on. A daemon that serves
# one echoes the bit on CONNECT_CONFIRM and appends a JSON descriptor
# data tail (e.g. {"shm": {"seg": <segment name>, "size": <bytes>}});
# the CLIENT then proves reachability (for shm: by actually attaching
# the named segment — same-host detection is attachability, never a
# hostname comparison). Decline-by-silence as ever: a flags=0 reply
# (un-upgraded v2 daemon, native C++ daemon) or an unattachable
# descriptor (cross-host pair) keeps the peer pair on tcp. With
# OCM_FABRIC unset/"tcp" the bit is never offered, so the default wire
# is byte-for-byte the pre-fabric protocol.
FLAG_CAP_FABRIC = 0x0200
# FLAG_HB_FWD marks a HEARTBEAT forwarded along a live-migration
# tombstone (elastic/): the receiver renews leases but must NEVER
# relay or re-forward it — the origin's relay branch triggering on a
# forwarded beat would loop (origin -> owner -> tombstone-forward ->
# origin -> ...), and two swapped migrations would ping-pong forever.
# With no migrations there are no tombstones and the bit never rides,
# so the static-membership heartbeat stays byte-identical.
FLAG_HB_FWD = 0x0400
# FLAG_CAP_MUX on CONNECT offers tagged request multiplexing
# (runtime/mux.py): once granted, the sender may interleave many
# in-flight requests on ONE connection, each carrying a u32 correlation
# id (FLAG_MUX_TAG), and the daemon may complete them OUT OF ORDER —
# every reply carries its request's tag back, so a response
# demultiplexer matches them regardless of completion order. Same
# offer/echo dance as every capability: a flags=0 reply (un-upgraded v2
# Python daemon, the native C++ daemon) declines by silence and the
# sender stays on the lockstep one-request-one-reply protocol over that
# same single connection. With OCM_MUX unset the bit is never offered,
# so the default wire is byte-for-byte the pre-mux protocol.
FLAG_CAP_MUX = 0x0800
# FLAG_MUX_TAG: the FIRST 4 bytes of the data tail are a u32 correlation
# id, NOT payload (prefixed OUTSIDE any trace context — strip order on
# receive is tag, then trace, then payload). Requests carry it only
# toward a peer that granted FLAG_CAP_MUX; the peer echoes the same tag
# on the reply (ERROR replies included — a typed rejection must reach
# the tenant that earned it, not a random waiter). A coalesced DATA_PUT
# burst tags only its CLOSING chunk: body chunks produce no reply and
# stay eligible for the zero-copy recv-into-arena landing.
FLAG_MUX_TAG = 0x1000
# FLAG_CAP_DEADLINE on CONNECT offers time-budget propagation
# (resilience/timebudget.py): once granted, requests may carry a
# FLAG_DEADLINE remaining-budget tail and the daemon refuses
# already-expired work with typed DEADLINE_EXCEEDED instead of serving
# it into the void. Same offer/echo dance as every capability: a
# flags=0 reply (un-upgraded v2 Python daemon, the native C++ daemon)
# declines by silence and the sender ships plain frames — budgets then
# only clamp the CLIENT's own ladders. With OCM_DEADLINE_MS unset the
# bit is never offered, so the default wire is byte-for-byte the
# pre-deadline protocol.
FLAG_CAP_DEADLINE = 0x2000
# FLAG_DEADLINE: a u32 data-tail prefix — the op's REMAINING time
# budget in milliseconds, measured by the SENDER at send time (each hop
# re-attaches the remainder on forwarded legs, so the budget decrements
# by observed elapsed time as it crosses the cluster; no clock sync
# needed, only monotonic local clocks). Strip order on receive is tag,
# then trace, then deadline, then payload — handlers see the same
# payload bytes they always did. Only ever set toward a peer that
# granted FLAG_CAP_DEADLINE.
FLAG_DEADLINE = 0x4000

# Which flag bits each message type may carry on the wire. pack() rejects
# undeclared bits (a typo'd flag must fail at the sender, not surface as
# peer misbehavior); receivers stay tolerant and just expose msg.flags.
# The analysis gate (analysis/project.py) checks every declared request
# bit against the daemon's handled-flags table, so a bit added here
# without daemon support fails lint rather than turning into silent
# lockstep behavior under load.
VALID_FLAGS: dict["MsgType", int] = {}


def _valid_flags(mtype: "MsgType") -> int:
    return VALID_FLAGS.get(mtype, 0)


class MsgType(enum.IntEnum):
    # app <-> local daemon (reference: pmsg mailbox messages)
    CONNECT = 1
    CONNECT_CONFIRM = 2
    DISCONNECT = 3
    # daemon <-> daemon control (reference: mem.c TCP messages)
    ADD_NODE = 10
    ADD_NODE_OK = 11
    REQ_ALLOC = 12          # origin -> rank 0: place this allocation
    ALLOC_PLACED = 13       # rank 0 -> origin: (rank, device, kind)
    DO_ALLOC = 14           # origin -> owner: reserve the extent
    DO_ALLOC_OK = 15        # owner -> origin: (alloc_id, offset)
    REQ_FREE = 16
    DO_FREE = 17
    FREE_OK = 18
    ALLOC_RESULT = 19       # local daemon -> app: the complete handle
    NOTE_FREE = 20          # owner -> rank 0: update placement accounting
    NOTE_ALLOC = 21         # restored owner -> rank 0: resync accounting
    RECLAIM_APP = 22        # origin daemon -> owner: free a dead app's allocs
    RECLAIM_APP_OK = 23
    # DCN data plane (reference: the per-fabric one-sided put/get)
    DATA_PUT = 30
    DATA_PUT_OK = 31
    DATA_GET = 32
    DATA_GET_OK = 33
    # liveness + observability (capability upgrades)
    HEARTBEAT = 40
    HEARTBEAT_OK = 41
    STATUS = 42
    STATUS_OK = 43
    # STATUS family extensions (obs/): Prometheus text exposition and the
    # structured event journal, served in-band so observability needs no
    # extra listening port. Replies carry the document as the data tail.
    STATUS_PROM = 44
    STATUS_PROM_OK = 45
    STATUS_EVENTS = 46
    STATUS_EVENTS_OK = 47
    # cross-process device plane: the SPMD controller's client registers
    # its plane endpoint (PLANE_SERVE -> master), and daemons relay
    # device-kind data ops to it as PLANE_PUT/PLANE_GET enriched with the
    # registry extent (replies reuse DATA_PUT_OK/DATA_GET_OK). This is how
    # a plane-less process (a C app over libocm, a second Python process)
    # reaches device bytes — the reference serves every arm cross-process
    # (alloc.c:151-222); here the daemon bridges to the controller.
    PLANE_SERVE = 50
    PLANE_SERVE_OK = 51
    PLANE_PUT = 52
    PLANE_GET = 53
    PLANE_SCRUB = 54
    # resilience (resilience/): daemon-to-daemon liveness, cluster-epoch
    # arbitration, k-way replica provisioning and failover repair. All new
    # types — a v2 peer that predates them never receives one (the client
    # capability gate is FLAG_CAP_REPLICA; liveness probes treat a typed
    # BAD_MSG ERROR reply as "alive, capability absent").
    PING = 60               # liveness probe; carries sender epoch+incarnation
    PING_OK = 61
    SUSPECT_NODE = 62       # non-master -> rank 0: I can't reach this rank
    SUSPECT_OK = 63
    EPOCH_UPDATE = 64       # rank 0 -> all: epoch bump + DEAD verdict (fence)
    EPOCH_OK = 65
    DO_REPLICA = 66         # provision a replica extent under a given id
    DO_REPLICA_OK = 67
    PROMOTE = 68            # rank 0 -> survivor: reconcile dead ranks
    PROMOTE_OK = 69
    RE_REPLICATE = 70       # rank 0 -> primary: copy an alloc to a new rank
    RE_REPLICATE_OK = 71
    # shm fabric control plane (fabric/shm.py). The DATA itself never
    # rides these frames — it is a one-sided memcpy through the peer's
    # mapped arena segment; these carry the registration lookup and the
    # validate/ack legs (role discipline, epoch fencing, bounds, replica
    # fan-out all stay on TCP, exactly the reference's split between the
    # allocation protocol and the per-fabric one-sided put/get). All new
    # types: only ever sent to a peer that granted FLAG_CAP_FABRIC, so a
    # v2/native peer never receives one.
    SHM_MAP = 72            # client -> owner: where does alloc_id live?
    SHM_MAP_OK = 73         # owner -> client: (ext_offset, ext_nbytes)
    SHM_PUT = 74            # "I wrote [off,off+n) via the segment": validate+ack
    SHM_GET = 75            # "may I read [off,off+n)?": validate before copy
    # elastic membership + live migration (elastic/). All new types: a
    # v2 peer that predates them answers a typed BAD_MSG ERROR (how the
    # native C++ daemon declines the whole family by silence), and with
    # no JOIN/LEAVE traffic none of them ever rides the wire — the
    # static-membership protocol stays byte-for-byte PR-7.
    REQ_JOIN = 76           # fresh daemon -> rank 0: admit me (addr+capacity)
    JOIN_OK = 77            # rank 0 -> joiner: (rank, epoch) + member table
    REQ_LEAVE = 78          # member -> rank 0: drain me, then drop me
    LEAVE_OK = 79           # rank 0 -> leaver: (epoch, extents moved off)
    MEMBER_UPDATE = 80      # rank 0 -> all: epoch bump + full member table
    MEMBER_OK = 81
    MIGRATE = 82            # rank 0 -> source primary: move alloc to target
    MIGRATE_OK = 83
    MIGRATE_BEGIN = 84      # source -> target: provision a QUARANTINED copy
    #                       (reply: DO_REPLICA_OK — same provision contract)
    REQ_LOCATE = 85         # client -> rank 0: where does alloc_id live NOW?
    LOCATE_OK = 86
    REQ_EXTENTS = 87        # rank 0 -> member: your host-kind inventory
    EXTENTS_OK = 88
    # Decentralized control plane (control/): the master role as an
    # epoch-fenced lease. All new types, only ever sent when
    # OCM_STANDBY_MASTERS > 0 arms leadership replication — with it
    # unset none of them ride, so the default wire stays byte-for-byte
    # PR-11. A v2/native peer answers typed BAD_MSG (decline by
    # silence), which just means "no standby there".
    MASTER_STATE = 89       # leader -> standby: replicated master state
    #                       (JSON + CRC32 trailer data tail, the
    #                       snapshot-v2 integrity discipline)
    MASTER_STATE_OK = 90
    LEADER_UPDATE = 91      # new leader -> all: leadership + epoch bump
    #                       (dead_rank/inc fence the deposed leader the
    #                       way EPOCH_UPDATE fences a dead owner;
    #                       dead_rank -1 = voluntary handoff, no fence)
    LEADER_OK = 92
    LEADER_HANDOFF = 93     # old leader -> successor: voluntary transfer
    #                       (final master state rides the data tail; a
    #                       CRC-failing tail REFUSES the handoff)
    # Server-side cancellation (resilience/timebudget.py + runtime/mux.py):
    # revoke a tagged in-flight op by its mux correlation id. A tenant
    # whose awaitable times out (or is cancelled) sends CANCEL instead of
    # only tombstoning the tag client-side; the daemon marks the tag
    # revoked — a queued op never dispatches, a completed op's reply is
    # suppressed (and a completed REQ_ALLOC's reservation is unwound
    # through the ordinary free path) — and answers CANCEL_OK with
    # whether anything was actually revoked. Only ever sent on a channel
    # that granted FLAG_CAP_MUX; the native C++ daemon answers typed
    # BAD_MSG with the stream in sync (the PR-8 unknown-type contract).
    CANCEL = 94
    CANCEL_OK = 95
    # failure
    ERROR = 99


# Kind tags on the wire (stable small ints, not Python enum identities).
WIRE_KIND = {
    "local_host": 0,
    "local_device": 1,
    "remote_device": 2,
    "remote_host": 3,
}
WIRE_KIND_INV = {v: k for k, v in WIRE_KIND.items()}

VALID_FLAGS.update({
    # Capability offer/echo bits. CONNECT may also carry the QoS profile
    # tail (FLAG_QOS_TAIL) alongside the FLAG_CAP_QOS offer; decliners
    # ignore both the bit and the tail. A TENANT's CONNECT riding an
    # already-multiplexed channel (one process hosting many app ids over
    # one connection) is itself a tagged request, hence FLAG_MUX_TAG.
    MsgType.CONNECT: (
        FLAG_CAP_COALESCE | FLAG_CAP_TRACE | FLAG_CAP_REPLICA
        | FLAG_CAP_QOS | FLAG_QOS_TAIL | FLAG_CAP_FABRIC
        | FLAG_CAP_MUX | FLAG_MUX_TAG | FLAG_CAP_DEADLINE
    ),
    MsgType.CONNECT_CONFIRM: (
        FLAG_CAP_COALESCE | FLAG_CAP_TRACE | FLAG_CAP_REPLICA
        | FLAG_CAP_QOS | FLAG_CAP_FABRIC | FLAG_CAP_MUX | FLAG_MUX_TAG
        | FLAG_CAP_DEADLINE
    ),
    # Requests that may carry a trace-context prefix once the peer
    # granted FLAG_CAP_TRACE. DATA_PUT also keeps the coalesced-burst
    # bit; its trace prefix rides the burst-CLOSING chunk only, so the
    # body chunks stay eligible for the zero-copy recv-into-arena path.
    # FLAG_MUX_TAG marks the client-facing request set a mux channel
    # interleaves (the same discipline: a burst tags only its closing
    # chunk).
    # FLAG_DEADLINE (the u32 remaining-budget prefix) rides the
    # budgetable op set: the client-facing data/alloc/free requests and
    # every hop they forward onto — the REQ_ALLOC leader relay, the
    # DO_ALLOC/DO_REPLICA provisioning legs, and the MIGRATE_BEGIN
    # migration leg — so an expiring budget is refused at whichever hop
    # it dies on, not served into the void.
    MsgType.DATA_PUT: (
        FLAG_MORE | FLAG_TRACE_CTX | FLAG_FANOUT | FLAG_MUX_TAG
        | FLAG_DEADLINE
    ),
    MsgType.DATA_GET: FLAG_TRACE_CTX | FLAG_MUX_TAG | FLAG_DEADLINE,
    MsgType.REQ_ALLOC: (
        FLAG_TRACE_CTX | FLAG_REPLICAS | FLAG_QOS_TAIL | FLAG_MUX_TAG
        | FLAG_DEADLINE
    ),
    MsgType.DO_ALLOC: FLAG_TRACE_CTX | FLAG_QOS_TAIL | FLAG_DEADLINE,
    MsgType.DO_REPLICA: FLAG_QOS_TAIL | FLAG_DEADLINE,
    # A migration-provisioned copy inherits the allocation's QoS class
    # (elastic/): non-default priorities ride the same u8 tail DO_REPLICA
    # carries; default-class migrations ship unchanged frames.
    MsgType.MIGRATE_BEGIN: FLAG_QOS_TAIL | FLAG_DEADLINE,
    MsgType.REQ_FREE: FLAG_TRACE_CTX | FLAG_MUX_TAG | FLAG_DEADLINE,
    MsgType.DO_FREE: FLAG_TRACE_CTX | FLAG_DEADLINE,
    MsgType.RECLAIM_APP: FLAG_TRACE_CTX,
    MsgType.NOTE_ALLOC: FLAG_TRACE_CTX,
    MsgType.NOTE_FREE: FLAG_TRACE_CTX,
    MsgType.HEARTBEAT: FLAG_TRACE_CTX | FLAG_HB_FWD | FLAG_MUX_TAG,
    MsgType.STATUS: FLAG_TRACE_CTX | FLAG_MUX_TAG,
    MsgType.STATUS_PROM: FLAG_TRACE_CTX | FLAG_MUX_TAG,
    MsgType.STATUS_EVENTS: FLAG_TRACE_CTX | FLAG_MUX_TAG,
    # Over a shared mux channel DISCONNECT is awaited like any request
    # (fire-and-forget would leave an unmatched reply to desync the
    # demux); REQ_LOCATE is part of the client failover ladder, which
    # runs over the channel too.
    MsgType.DISCONNECT: FLAG_MUX_TAG,
    MsgType.REQ_LOCATE: FLAG_MUX_TAG,
    # CANCEL rides the mux channel as an ordinary tagged request (its
    # OWN tag; the victim tag is a payload field) so its ack demuxes
    # like any reply.
    MsgType.CANCEL: FLAG_MUX_TAG,
    MsgType.CANCEL_OK: FLAG_MUX_TAG,
    # Replies: a request that arrived tagged is answered tagged — the
    # echo is what lets the demultiplexer match out-of-order
    # completions. ERROR included: typed rejections (BUSY, MOVED,
    # QUOTA_EXCEEDED) must reach exactly the tenant that earned them.
    MsgType.ALLOC_RESULT: FLAG_MUX_TAG,
    MsgType.FREE_OK: FLAG_MUX_TAG,
    MsgType.DATA_PUT_OK: FLAG_MUX_TAG,
    MsgType.DATA_GET_OK: FLAG_MUX_TAG,
    MsgType.HEARTBEAT_OK: FLAG_MUX_TAG,
    MsgType.STATUS_OK: FLAG_MUX_TAG,
    MsgType.STATUS_PROM_OK: FLAG_MUX_TAG,
    MsgType.STATUS_EVENTS_OK: FLAG_MUX_TAG,
    MsgType.LOCATE_OK: FLAG_MUX_TAG,
    MsgType.ERROR: FLAG_MUX_TAG,
    # shm fabric control legs are ordinary traceable requests: the
    # exported trace shows the validate/ack hop where a DATA_* span
    # would have been.
    MsgType.SHM_MAP: FLAG_TRACE_CTX,
    MsgType.SHM_PUT: FLAG_TRACE_CTX,
    MsgType.SHM_GET: FLAG_TRACE_CTX,
})


def _pack_str(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) > 0xFFFF:
        raise OcmProtocolError("string field too long")
    return struct.pack("<H", len(b)) + b


def _unpack_str(buf, off: int) -> tuple[str, int]:
    (n,) = struct.unpack_from("<H", buf, off)
    off += 2
    if off + n > len(buf):  # a silent short slice would hide truncation
        raise OcmProtocolError("truncated string field")
    return bytes(buf[off : off + n]).decode("utf-8"), off + n


@dataclass
class Message:
    type: MsgType
    fields: dict = field(default_factory=dict)
    # On SEND, ``data`` may also be a list/tuple of buffers — the vectored
    # form obs/trace.attach uses to prefix a 16-byte trace context onto a
    # bulk payload without copying it (send_msg scatter-gathers the parts;
    # the wire bytes are identical to the concatenation). Received
    # messages always carry one contiguous buffer.
    data: bytes = b""
    flags: int = 0  # header-flag bits (FLAG_*), preserved by the codec

    def __repr__(self) -> str:  # data elided for log hygiene
        fl = f", flags={self.flags:#x}" if self.flags else ""
        return (
            f"Message({self.type.name}, {self.fields}, "
            f"data={_data_len(self.data)}B{fl})"
        )


def _data_parts(data) -> list:
    return list(data) if isinstance(data, (list, tuple)) else [data]


def _data_len(data) -> int:
    if isinstance(data, (list, tuple)):
        return sum(len(p) for p in data)
    return len(data)


# Payload schemas: (field_name, struct_char or "s" for string) in order.
# "q" = i64, "Q" = u64, "I" = u32, "B" = u8, "d" = f64, "s" = string.
_SCHEMAS: dict[MsgType, list[tuple[str, str]]] = {
    MsgType.CONNECT: [("pid", "q"), ("rank", "q")],
    MsgType.CONNECT_CONFIRM: [("rank", "q"), ("nnodes", "q")],
    # "owners" on DISCONNECT/HEARTBEAT is the comma-separated set of ranks
    # holding this app's remote allocations, tracked app-side (the app is
    # the source of truth for its own handles, and the set survives daemon
    # restarts). Bounds reclamation/relay fan-out to O(owners), not
    # O(nnodes).
    MsgType.DISCONNECT: [("pid", "q"), ("owners", "s")],
    MsgType.ADD_NODE: [
        ("rank", "q"),
        ("host", "s"),
        ("port", "I"),
        ("ndevices", "I"),
        ("device_arena_bytes", "Q"),
        ("host_arena_bytes", "Q"),
    ],
    MsgType.ADD_NODE_OK: [("nnodes", "q")],
    MsgType.REQ_ALLOC: [
        ("orig_rank", "q"),
        ("pid", "q"),
        ("kind", "B"),
        ("nbytes", "Q"),
    ],
    MsgType.ALLOC_PLACED: [
        ("rank", "q"),
        ("device_index", "I"),
        ("kind", "B"),
    ],
    MsgType.DO_ALLOC: [
        ("orig_rank", "q"),
        ("pid", "q"),
        ("kind", "B"),
        ("device_index", "I"),
        ("nbytes", "Q"),
    ],
    MsgType.DO_ALLOC_OK: [("alloc_id", "Q"), ("offset", "Q")],
    MsgType.REQ_FREE: [("alloc_id", "Q"), ("rank", "q")],
    MsgType.ALLOC_RESULT: [
        ("alloc_id", "Q"),
        ("rank", "q"),
        ("device_index", "I"),
        ("kind", "B"),
        ("offset", "Q"),
        ("nbytes", "Q"),
        ("owner_host", "s"),
        ("owner_port", "I"),
    ],
    MsgType.NOTE_FREE: [
        ("kind", "B"),
        ("rank", "q"),
        ("device_index", "I"),
        ("nbytes", "Q"),
    ],
    MsgType.NOTE_ALLOC: [
        ("kind", "B"),
        ("rank", "q"),
        ("device_index", "I"),
        ("nbytes", "Q"),
    ],
    MsgType.DO_FREE: [("alloc_id", "Q")],
    MsgType.FREE_OK: [("alloc_id", "Q")],
    MsgType.RECLAIM_APP: [("pid", "q"), ("rank", "q")],
    MsgType.RECLAIM_APP_OK: [("count", "Q")],
    MsgType.DATA_PUT: [("alloc_id", "Q"), ("offset", "Q"), ("nbytes", "Q")],
    MsgType.DATA_PUT_OK: [("nbytes", "Q")],
    MsgType.DATA_GET: [("alloc_id", "Q"), ("offset", "Q"), ("nbytes", "Q")],
    MsgType.DATA_GET_OK: [("nbytes", "Q")],
    MsgType.HEARTBEAT: [("rank", "q"), ("pid", "q"), ("owners", "s")],
    MsgType.HEARTBEAT_OK: [("lease_s", "d")],
    MsgType.STATUS: [],
    # Prometheus text exposition / event-journal JSONL ride as the reply
    # data tail (documents, not fields — same pattern as STATUS_OK's
    # telemetry tail).
    MsgType.STATUS_PROM: [],
    MsgType.STATUS_PROM_OK: [("rank", "q")],
    MsgType.STATUS_EVENTS: [],
    MsgType.STATUS_EVENTS_OK: [("rank", "q"), ("count", "Q")],
    MsgType.STATUS_OK: [
        ("rank", "q"),
        ("nnodes", "q"),
        ("live_allocs", "Q"),
        ("host_bytes_live", "Q"),
        ("device_bytes_live", "Q"),
    ],
    # "relay" = 0 from the registering client, 1 daemon-to-daemon (the
    # forward-to-master and master-broadcast legs never re-forward).
    MsgType.PLANE_SERVE: [("host", "s"), ("port", "I"), ("relay", "B")],
    MsgType.PLANE_SERVE_OK: [("port", "I")],
    # The daemon->plane relay legs carry the registry extent so the plane
    # controller can address its arena without a registry of its own.
    MsgType.PLANE_PUT: [
        ("alloc_id", "Q"),
        ("rank", "q"),
        ("device_index", "I"),
        ("ext_offset", "Q"),
        ("ext_nbytes", "Q"),
        ("offset", "Q"),
        ("nbytes", "Q"),
    ],
    MsgType.PLANE_GET: [
        ("alloc_id", "Q"),
        ("rank", "q"),
        ("device_index", "I"),
        ("ext_offset", "Q"),
        ("ext_nbytes", "Q"),
        ("offset", "Q"),
        ("nbytes", "Q"),
    ],
    # Owner-daemon -> plane: zero a recycled device extent at free time
    # (O(1) wire; the device twin of "host arms are scrubbed at free time
    # by the owner daemon"). Reply: DATA_PUT_OK.
    MsgType.PLANE_SCRUB: [
        ("alloc_id", "Q"),
        ("rank", "q"),
        ("device_index", "I"),
        ("ext_offset", "Q"),
        ("ext_nbytes", "Q"),
    ],
    # Resilience family (resilience/). "inc" is the sender's incarnation —
    # a random u64 minted per daemon object, so a DEAD verdict can fence
    # exactly the process it was issued against (a restarted daemon on the
    # same port carries a fresh incarnation and is never falsely fenced).
    MsgType.PING: [("rank", "q"), ("epoch", "Q"), ("inc", "Q")],
    MsgType.PING_OK: [("rank", "q"), ("epoch", "Q"), ("inc", "Q")],
    MsgType.SUSPECT_NODE: [("rank", "q"), ("reporter", "q"), ("epoch", "Q")],
    # "state" is the arbiter's PeerState verdict (resilience/detector.py
    # wire values: 0 ALIVE, 1 SUSPECT, 2 DEAD).
    MsgType.SUSPECT_OK: [("epoch", "Q"), ("state", "B")],
    MsgType.EPOCH_UPDATE: [("epoch", "Q"), ("dead_rank", "q"), ("inc", "Q")],
    MsgType.EPOCH_OK: [("epoch", "Q")],
    # "chain" is the ordered comma-separated owner chain "primary,r1,...";
    # every holder of a replicated allocation records it, so promotion on
    # a DEAD verdict is a deterministic local computation.
    MsgType.DO_REPLICA: [
        ("alloc_id", "Q"),
        ("kind", "B"),
        ("nbytes", "Q"),
        ("orig_rank", "q"),
        ("pid", "q"),
        ("chain", "s"),
        ("epoch", "Q"),
    ],
    MsgType.DO_REPLICA_OK: [("alloc_id", "Q"), ("offset", "Q")],
    MsgType.PROMOTE: [("dead_ranks", "s"), ("epoch", "Q")],
    # PROMOTE_OK carries a JSON data tail listing the allocations this
    # rank is now primary for that lost copies (re-replication work list).
    MsgType.PROMOTE_OK: [("count", "Q")],
    MsgType.RE_REPLICATE: [
        ("alloc_id", "Q"),
        ("target_rank", "q"),
        ("epoch", "Q"),
    ],
    MsgType.RE_REPLICATE_OK: [("alloc_id", "Q"), ("nbytes", "Q")],
    # shm fabric control (fabric/shm.py). Every leg names the SEGMENT
    # the client attached ("seg"): a daemon that restarted on the same
    # host:port serves a fresh segment under the same alloc_ids
    # (snapshot restore), and without the identity check it would bless
    # a memcpy that landed in the dead daemon's orphaned mapping. A
    # mismatch answers STALE_EPOCH — the failover signal — so the
    # client re-negotiates instead of trusting the stale region.
    # SHM_PUT/SHM_GET additionally carry the ext_offset the client's
    # cached mapping used, so the owner can refuse a STALE mapping
    # (extent freed and recycled since SHM_MAP) with BAD_ALLOC_ID
    # instead of blessing a write that landed on the wrong tenant's
    # bytes. "offset" is handle-relative, as on DATA_*. Replies:
    # SHM_PUT -> DATA_PUT_OK, SHM_GET -> DATA_GET_OK (the get reply
    # carries NO payload — the client copies from the segment after
    # the validation lands).
    MsgType.SHM_MAP: [("alloc_id", "Q"), ("seg", "s")],
    MsgType.SHM_MAP_OK: [
        ("alloc_id", "Q"),
        ("ext_offset", "Q"),
        ("ext_nbytes", "Q"),
    ],
    MsgType.SHM_PUT: [
        ("alloc_id", "Q"),
        ("ext_offset", "Q"),
        ("offset", "Q"),
        ("nbytes", "Q"),
        ("seg", "s"),
    ],
    MsgType.SHM_GET: [
        ("alloc_id", "Q"),
        ("ext_offset", "Q"),
        ("offset", "Q"),
        ("nbytes", "Q"),
        ("seg", "s"),
    ],
    # Elastic membership (elastic/). REQ_JOIN announces the joiner's
    # peer-reachable address, capacities and incarnation (the same
    # triple ADD_NODE carries, plus "inc" so rank 0 can tell a restarted
    # daemon on a reused address from a duplicate). JOIN_OK and
    # MEMBER_UPDATE carry the full epoch-stamped member table as a JSON
    # data tail (membership.ClusterView.to_wire) — a table, not fixed
    # fields, because the row count changes by definition.
    MsgType.REQ_JOIN: [
        ("host", "s"),
        ("port", "I"),
        ("ndevices", "I"),
        ("device_arena_bytes", "Q"),
        ("host_arena_bytes", "Q"),
        ("inc", "Q"),
    ],
    MsgType.JOIN_OK: [("rank", "q"), ("epoch", "Q"), ("nnodes", "q")],
    MsgType.REQ_LEAVE: [("rank", "q"), ("inc", "Q")],
    MsgType.LEAVE_OK: [("epoch", "Q"), ("moved", "Q")],
    MsgType.MEMBER_UPDATE: [("epoch", "Q")],
    MsgType.MEMBER_OK: [("epoch", "Q")],
    # Live migration: rank 0's rebalancer drives MIGRATE at the source
    # primary, which runs the provision -> stream -> flip -> drop-source
    # state machine (daemon._on_migrate). MIGRATE_BEGIN provisions the
    # target's copy QUARANTINED (refuses client ops, aborted if the
    # source dies mid-stream) under the source's chain + itself;
    # "src_rank" is the abort key. Replies with DO_REPLICA_OK.
    MsgType.MIGRATE: [
        ("alloc_id", "Q"),
        ("target_rank", "q"),
        ("epoch", "Q"),
    ],
    MsgType.MIGRATE_OK: [("alloc_id", "Q"), ("nbytes", "Q")],
    MsgType.MIGRATE_BEGIN: [
        ("alloc_id", "Q"),
        ("kind", "B"),
        ("nbytes", "Q"),
        ("orig_rank", "q"),
        ("pid", "q"),
        ("chain", "s"),
        ("src_rank", "q"),
        ("epoch", "Q"),
    ],
    # Handle re-resolution: a client whose ladder dead-ends (owner
    # migrated away, maybe departed entirely) asks rank 0 where the
    # allocation lives now. The reply names the primary's address
    # explicitly — the rank may postdate the client's boot membership.
    MsgType.REQ_LOCATE: [("alloc_id", "Q")],
    MsgType.LOCATE_OK: [
        ("alloc_id", "Q"),
        ("rank", "q"),
        ("host", "s"),
        ("port", "I"),
        ("chain", "s"),
    ],
    # Rebalancer inventory: the member's host-kind registry entries as a
    # JSON data tail (id, nbytes, chain, priority, origin) — what the
    # capacity-weighted planner and the LEAVE drain walk.
    MsgType.REQ_EXTENTS: [],
    MsgType.EXTENTS_OK: [("rank", "q"), ("count", "Q")],
    # Decentralized control plane (control/leader.py). MASTER_STATE's
    # data tail is the leader's replicated coordination state (placement
    # accounting, member view, dead set) as JSON with a trailing CRC32 —
    # the snapshot-v2 discipline, so a standby can refuse a torn copy
    # WHOLE instead of leading from it. "seq" is the push sequence
    # (monotonic per leader incarnation); stale pushes are dropped.
    MsgType.MASTER_STATE: [("seq", "Q"), ("epoch", "Q"), ("leader", "q")],
    MsgType.MASTER_STATE_OK: [("seq", "Q")],
    # LEADER_UPDATE: the election/handoff broadcast. "dead_rank"/"inc"
    # fence the deposed leader by (rank, incarnation) exactly like
    # EPOCH_UPDATE fences a dead owner; dead_rank -1 marks a voluntary
    # handoff (nobody is fenced). Receivers adopt the leader, evict the
    # dead leader's pooled connections, and re-aim master-bound traffic.
    MsgType.LEADER_UPDATE: [
        ("leader", "q"),
        ("epoch", "Q"),
        ("dead_rank", "q"),
        ("inc", "Q"),
    ],
    MsgType.LEADER_OK: [("epoch", "Q")],
    MsgType.LEADER_HANDOFF: [
        ("leader", "q"),
        ("epoch", "Q"),
        ("from_rank", "q"),
        ("inc", "Q"),
    ],
    # Server-side cancellation: "tag" is the VICTIM op's mux correlation
    # id on this same connection. "revoked" on the ack: 1 when the
    # daemon actually revoked something (queued op skipped, or a
    # completed op's reply suppressed), 0 when the tag was unknown /
    # already answered / an inline data leg past the point of no return.
    MsgType.CANCEL: [("tag", "I")],
    MsgType.CANCEL_OK: [("tag", "I"), ("revoked", "B")],
    MsgType.ERROR: [("code", "I"), ("detail", "s")],
}


class ErrCode(enum.IntEnum):
    UNKNOWN = 0
    OOM = 1
    BAD_ALLOC_ID = 2
    BOUNDS = 3
    BAD_MSG = 4
    PLACEMENT = 5
    # A master-bound message (ADD_NODE, REQ_JOIN, SUSPECT_NODE, ...)
    # reached a daemon that is not the current leader. Once leadership
    # is dynamic (control/: OCM_STANDBY_MASTERS > 0, or the leader ever
    # moved off rank 0) the ERROR frame's data tail names the CURRENT
    # leader — i64 rank, then host (u16-length string) + u32 port —
    # which request() surfaces as OcmRemoteError.leader_rank /
    # .leader_addr so senders re-aim instead of spinning (the MOVED
    # redirect pattern applied to the master role). Static clusters
    # ship the tail-less PR-11 frame.
    NOT_MASTER = 6
    # The serving daemon was fenced by a newer cluster epoch (a DEAD
    # verdict it outlived): it must not serve data or grant extents, and
    # clients treat this as a failover signal, retrying via the replica
    # chain instead of surfacing an application error.
    STALE_EPOCH = 7
    # A replica refused a CLIENT data op because it still believes its
    # primary alive (accepting would fork the copies). Retryable: the
    # client re-walks its failover ladder — by the time the primary's
    # death verdict lands, the replica starts serving.
    NOT_PRIMARY = 8
    # A primary could not reach a replica that is not (yet) declared
    # DEAD, so it cannot honor the replication contract for this write.
    # Retryable: the detector resolves the replica's fate within a few
    # probe intervals, after which the put either fans out or degrades.
    REPLICA_UNAVAILABLE = 9
    # QoS admission control (qos/): the app's byte or handle quota
    # cannot admit this allocation. Not retryable until the app frees —
    # the quota is the app's own budget, not a transient condition.
    QUOTA_EXCEEDED = 10
    # Admission control refused the app outright (e.g. the daemon's
    # concurrent-app cap is reached). Retrying only helps once other
    # apps disconnect or go stale.
    ADMISSION_DENIED = 11
    # Back-pressure: the arena(s) crossed the high watermark. Retryable;
    # the ERROR frame's data tail carries a u32 server-suggested backoff
    # in milliseconds, which request() surfaces as
    # OcmRemoteError.retry_after_ms.
    BUSY = 12
    # Live migration (elastic/): the allocation was migrated off this
    # rank; the data tail carries the new owner rank as an i64, which
    # request() surfaces as OcmRemoteError.moved_to_rank. Retryable by
    # definition — the client repoints its handle at the named rank and
    # re-runs, exactly the failover-ladder contract.
    MOVED = 13
    # Time budget (resilience/timebudget.py): the op's propagated
    # deadline expired before (or while) this daemon could serve it —
    # "The Tail at Scale"'s fail-fast contract. NOT retryable: the
    # budget is the caller's own clock, and every retry ladder must
    # surface it typed instead of burning the remaining window.
    DEADLINE_EXCEEDED = 14


# Precompiled one-shot codecs for string-free schemas: the per-frame
# encode/decode is the control-plane hot path (a mux channel moves
# thousands of tiny frames per second), and compiling a struct.Struct
# per FIELD per frame dominated it. Filled after _SCHEMAS below.
_FIXED_CODEC: dict["MsgType", tuple[struct.Struct, tuple[str, ...]]] = {}


def _pack_prefix(msg: Message) -> bytes:
    """Header + encoded fields ONLY (the frame length still counts
    msg.data) — shared by pack() and send_msg's scatter-gather fast path
    so the wire encoding has exactly one implementation (protocol.cc's
    pack_prefix twin)."""
    if msg.type not in _SCHEMAS:
        raise OcmProtocolError(f"no schema for {msg.type}")
    if msg.flags & ~_valid_flags(msg.type):
        raise OcmProtocolError(
            f"flags {msg.flags:#x} invalid for {msg.type.name} "
            f"(allowed mask {_valid_flags(msg.type):#x})"
        )
    fixed = _FIXED_CODEC.get(msg.type)
    if fixed is not None:
        st, names = fixed
        f = msg.fields
        try:
            fields = st.pack(*(f[n] for n in names))
        except (KeyError, struct.error) as e:
            raise OcmProtocolError(
                f"bad {msg.type.name} fields: {e}"
            ) from e
        plen = st.size + _data_len(msg.data)
        if plen > MAX_PAYLOAD:
            raise OcmProtocolError(f"payload {plen} exceeds cap")
        return HEADER.pack(
            MAGIC, VERSION, int(msg.type), msg.flags, plen
        ) + fields
    schema = _SCHEMAS[msg.type]
    fields = bytearray()
    for name, fmt in schema:
        v = msg.fields[name]
        if fmt == "s":
            fields += _pack_str(v)
        else:
            fields += struct.pack("<" + fmt, v)
    plen = len(fields) + _data_len(msg.data)
    if plen > MAX_PAYLOAD:
        raise OcmProtocolError(f"payload {plen} exceeds cap")
    return HEADER.pack(MAGIC, VERSION, int(msg.type), msg.flags, plen) + fields


def pack(msg: Message) -> bytes:
    return _pack_prefix(msg) + b"".join(
        bytes(p) for p in _data_parts(msg.data)
    )


def _parse_fields(mtype: MsgType, payload) -> tuple[dict, int]:
    """Parse the schema'd fields; returns (fields, data offset). The
    payload is untrusted wire input: truncated fields and invalid UTF-8
    must surface as protocol errors, not struct/unicode internals."""
    fixed = _FIXED_CODEC.get(mtype)
    if fixed is not None:
        st, names = fixed
        try:
            values = st.unpack_from(payload, 0)
        except struct.error as e:
            raise OcmProtocolError(
                f"malformed {mtype.name} payload: {e}"
            ) from e
        return dict(zip(names, values)), st.size
    schema = _SCHEMAS[mtype]
    fields: dict = {}
    off = 0
    try:
        for name, fmt in schema:
            if fmt == "s":
                fields[name], off = _unpack_str(payload, off)
            else:
                st = struct.Struct("<" + fmt)
                (fields[name],) = st.unpack_from(payload, off)
                off += st.size
    except (struct.error, UnicodeDecodeError) as e:
        raise OcmProtocolError(
            f"malformed {mtype.name} payload: {e}"
        ) from e
    return fields, off


def _unpack_fields(mtype: MsgType, fields_buf) -> Message:
    fields, _ = _parse_fields(mtype, fields_buf)
    return Message(mtype, fields, b"")


def unpack(header: bytes, payload: bytes) -> Message:
    try:
        magic, version, mtype, flags, plen = HEADER.unpack(header)
    except struct.error as e:
        raise OcmProtocolError(f"short header: {e}") from e
    if magic != MAGIC:
        raise OcmProtocolError(f"bad magic {magic!r}")
    if version != VERSION:
        raise OcmProtocolError(f"unsupported protocol version {version}")
    if plen != len(payload):
        raise OcmProtocolError("length mismatch")
    try:
        mtype = MsgType(mtype)
    except ValueError as e:
        raise OcmProtocolError(f"unknown message type {mtype}") from e
    fields, off = _parse_fields(mtype, payload)
    # Bulk payloads stay a zero-copy view into the receive buffer (an
    # 8 MiB DATA_PUT chunk would otherwise be copied once more here);
    # small ones become plain bytes, the friendliest type for callers.
    n_data = len(payload) - off
    data = (
        memoryview(payload)[off:] if n_data >= (64 << 10)
        else bytes(payload[off:])
    )
    # Receivers are TOLERANT of unknown flag bits (only senders validate):
    # the bits are exposed as-is and handlers act on the ones they know.
    return Message(mtype, fields, data, flags=flags)


# -- blocking socket transport (conn_put/conn_get analogue, sock.c:215-253) --


def _sendall_vec(sock: socket.socket, parts: list) -> None:
    """sendall over a list of buffers WITHOUT concatenating them — the
    bulk-data fast path (a DATA_PUT frame is header+fields plus an 8 MiB
    payload; building one contiguous frame copies the payload twice)."""
    views = [memoryview(p) for p in parts if len(p)]
    while views:
        sent = sock.sendmsg(views)
        while views and sent >= len(views[0]):
            sent -= len(views[0])
            views.pop(0)
        if views and sent:
            views[0] = views[0][sent:]


def send_msg(sock: socket.socket, msg: Message) -> None:
    prefix = _pack_prefix(msg)
    n_data = _data_len(msg.data)
    if n_data >= (64 << 10):
        _sendall_vec(sock, [prefix, *_data_parts(msg.data)])
    elif n_data:
        sock.sendall(
            prefix + b"".join(bytes(p) for p in _data_parts(msg.data))
        )
    else:
        sock.sendall(prefix)


def _recv_into(sock: socket.socket, view: memoryview,
               eof_ok: bool = False) -> bool:
    """Fill ``view`` exactly. ``eof_ok`` permits a clean EOF *before the
    first byte* (returns False) — EOF mid-message always raises."""
    n = len(view)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], min(n - got, 1 << 20))
        if r == 0:
            if eof_ok and got == 0:
                return False
            raise OcmProtocolError("peer closed mid-message")
        got += r
    return True


def _recv_exact(sock: socket.socket, n: int, eof_ok: bool = False):
    """Read exactly n bytes into one fresh buffer (no chunk-list join)."""
    buf = bytearray(n)
    if not _recv_into(sock, memoryview(buf), eof_ok=eof_ok):
        return b""
    return buf


class BufferedSock:
    """Read-side buffering shim over a connected socket: ``recv_into``
    is served from an internal buffer refilled by large kernel reads —
    one recv syscall per ~64 KiB of small frames instead of 2-3 per
    frame (header, fields, payload). The small-op serving hot path (mux
    channels pipeline thousands of tiny tagged requests per second onto
    one connection) is syscall-bound without this. Bulk reads bypass the
    buffer whenever it is empty, so large DATA_PUT payloads keep their
    single recv-into-arena landing. The send side is untouched — pass
    the REAL socket to send_msg."""

    __slots__ = ("sock", "_buf", "_pos")

    CAP = 64 << 10

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._buf = b""
        self._pos = 0

    def buffered(self) -> int:
        """Bytes already read off the kernel but not yet consumed — the
        serve loop's "more requests are in hand" signal (reply batching
        flushes only once this drains, so pipelined peers get one
        writev per burst of replies)."""
        return len(self._buf) - self._pos

    def recv_into(self, view, nbytes: int = 0) -> int:
        n = nbytes or len(view)
        avail = len(self._buf) - self._pos
        if avail > 0:
            take = min(avail, n)
            view[:take] = memoryview(self._buf)[self._pos:self._pos + take]
            self._pos += take
            return take
        if n >= self.CAP:
            # Bulk payload with an empty buffer: straight into the
            # caller's destination (the zero-copy landing).
            return self.sock.recv_into(view, n)
        data = self.sock.recv(self.CAP)
        if not data:
            return 0
        take = min(len(data), n)
        view[:take] = memoryview(data)[:take]
        if take < len(data):
            self._buf = data
            self._pos = take
        else:
            self._buf = b""
            self._pos = 0
        return take


class RecvScratch:
    """Reusable receive buffer for the data-plane hot loops: a fresh
    bytearray per 8 MiB reply chunk costs an allocation + kernel zeroing
    each time. A payload decoded into scratch is a VIEW valid only until
    the next recv on the same socket — use only where the message is
    fully consumed before the next receive (the pipelined client loop,
    the daemon serve loop)."""

    __slots__ = ("buf",)

    def __init__(self) -> None:
        self.buf = bytearray()

    def get(self, n: int) -> memoryview:
        if len(self.buf) < n:
            self.buf = bytearray(max(n, 2 * len(self.buf)))
        elif len(self.buf) > (32 << 20) and n < len(self.buf) // 4:
            # Don't pin a burst's high-water buffer on a long-lived
            # connection that went back to small messages.
            self.buf = bytearray(n)
        return memoryview(self.buf)[:n]


# Encoded size of each type's fields when the schema is fixed-width
# (absent when it contains strings): lets recv_msg land a bulk payload's
# data STRAIGHT in the caller's destination buffer.
_FIXED_FIELD_SIZE: dict[MsgType, int] = {
    t: sum(struct.calcsize("<" + fmt) for _, fmt in schema)
    for t, schema in _SCHEMAS.items()
    if all(fmt != "s" for _, fmt in schema)
}

# One precompiled Struct + field-name tuple per string-free schema (the
# hot-path codec _pack_prefix/_parse_fields dispatch through).
_FIXED_CODEC.update({
    t: (
        struct.Struct("<" + "".join(fmt for _, fmt in schema)),
        tuple(name for name, _ in schema),
    )
    for t, schema in _SCHEMAS.items()
    if schema and all(fmt != "s" for _, fmt in schema)
})


def recv_msg(
    sock: socket.socket,
    scratch: RecvScratch | None = None,
    data_into: memoryview | None = None,
    data_router=None,
) -> Message:
    """Receive one message. With ``data_into`` (pipelined readers that
    know the expected reply), a fixed-field message whose data length
    matches lands its payload DIRECTLY in that buffer — ``Message.data``
    IS ``data_into`` then (identity-comparable by the caller); any other
    message (an ERROR reply, a length mismatch) falls back to the normal
    path untouched.

    ``data_router`` is the server-side twin for readers that DON'T know
    what arrives next: called as ``data_router(msg, n_data)`` after the
    fixed fields of a bulk message are decoded (but before its payload is
    read), it may return a writable memoryview of exactly ``n_data``
    bytes to land the payload into (e.g. the destination arena extent of
    a DATA_PUT — the recv IS the write, no scratch hop, no copy). The
    returned message then has ``data_landed = True`` set on it. Any
    ``None``/mis-sized return or router exception falls back to the
    scratch path; string-schema'd types bypass routing entirely."""
    header = _recv_exact(sock, HEADER.size, eof_ok=True)
    if not header:
        # Clean disconnect at a frame boundary — ordinary, not an anomaly.
        raise OcmProtocolError("peer closed")
    magic, version, mtype_raw, flags, plen = HEADER.unpack(header)
    if plen > MAX_PAYLOAD:
        raise OcmProtocolError(f"advertised payload {plen} exceeds cap")
    if data_into is not None and magic == MAGIC and version == VERSION:
        # Magic/version checked HERE (the normal path does it in unpack):
        # a corrupt or wrong-version frame must raise, never land bytes
        # in the caller's buffer.
        try:
            mt = MsgType(mtype_raw)
            ffix = _FIXED_FIELD_SIZE.get(mt)
        except ValueError:
            ffix = None  # unknown type: let unpack raise the real error
        if ffix is not None and plen - ffix == len(data_into):
            fields = _recv_exact(sock, ffix) if ffix else b""
            _recv_into(sock, data_into)
            msg = _unpack_fields(mt, fields)
            msg.data = data_into
            msg.flags = flags
            return msg
    if data_router is not None and magic == MAGIC and version == VERSION:
        try:
            mt = MsgType(mtype_raw)
            ffix = _FIXED_FIELD_SIZE.get(mt)
        except ValueError:
            ffix = None  # unknown type: let unpack raise the real error
        if ffix is not None and plen >= ffix:
            fields_buf = _recv_exact(sock, ffix) if ffix else b""
            msg = _unpack_fields(mt, fields_buf)
            msg.flags = flags
            n_data = plen - ffix
            if n_data == 0:
                return msg
            sink = None
            try:
                sink = data_router(msg, n_data)
            except Exception:  # noqa: BLE001 — routing is best-effort;
                sink = None  # the handler re-raises the real error later
            if sink is not None and len(sink) == n_data:
                _recv_into(sock, sink)
                msg.data = sink
                msg.data_landed = True
                return msg
            if scratch is not None and n_data >= (64 << 10):
                payload = scratch.get(n_data)
                _recv_into(sock, payload)
                msg.data = payload
            else:
                msg.data = bytes(_recv_exact(sock, n_data))
            return msg
    if plen == 0:
        payload = b""
    elif scratch is not None and plen >= (64 << 10):
        payload = scratch.get(plen)
        _recv_into(sock, payload)
    else:
        payload = _recv_exact(sock, plen)
    return unpack(header, payload)


def remote_error(reply: Message) -> OcmRemoteError:
    """Build the typed OcmRemoteError for an ERROR reply, including the
    code-specific data tails — a BUSY retry hint (u32 ms) and a MOVED
    live-migration redirect (i64 new owner rank). EVERY path that turns
    an ERROR frame into an exception must come through here: an error
    built from code+detail alone silently drops the redirect, and the
    client ladder then spins on the old owner instead of following it."""
    code = reply.fields["code"]
    detail = reply.fields["detail"]
    if code in ErrCode._value2member_map_:
        detail = f"{ErrCode(code).name}: {detail}"
    err = OcmRemoteError(code, detail)
    # A BUSY rejection carries the server-suggested backoff as a u32
    # (milliseconds) data tail — the retry hint back-pressured clients
    # honor (qos/). A MOVED rejection names the new owner rank as an
    # i64 tail (elastic/). Other codes never carry one; a short or
    # absent tail just means "no hint".
    if code == int(ErrCode.BUSY) and len(reply.data) >= 4:
        (err.retry_after_ms,) = struct.unpack_from("<I", reply.data, 0)
    if code == int(ErrCode.MOVED) and len(reply.data) >= 8:
        (err.moved_to_rank,) = struct.unpack_from("<q", reply.data, 0)
    if code == int(ErrCode.STALE_EPOCH) and len(reply.data) >= 16:
        # A PING answered with a DEAD verdict carries the verdict
        # holder's authority as a (leader_epoch u64, epoch u64) tail:
        # the probing daemon fences itself only when that authority
        # exceeds its own — a deposed leader's stale claim must never
        # fence a survivor (control/).
        (err.verdict_leader_epoch, err.verdict_epoch) = struct.unpack_from(
            "<QQ", reply.data, 0
        )
    if code == int(ErrCode.NOT_MASTER) and len(reply.data) >= 8:
        # Leader redirect (control/): rank, then optionally the leader's
        # explicit address (a joiner bounced off a non-leader seed has
        # no member table to resolve the rank through).
        (err.leader_rank,) = struct.unpack_from("<q", reply.data, 0)
        err.leader_addr = None
        try:
            host, off = _unpack_str(reply.data, 8)
            (port,) = struct.unpack_from("<I", reply.data, off)
            if host and port:
                err.leader_addr = (host, port)
        except (OcmProtocolError, struct.error):
            pass  # rank-only tail from a terser sender
    return err


# -- mux correlation tags (runtime/mux.py) -------------------------------

_TAG = struct.Struct("<I")
TAG_BYTES = _TAG.size  # 4


def attach_tag(msg: Message, tag: int) -> Message:
    """Prefix ``msg``'s data tail with a u32 correlation id and set
    FLAG_MUX_TAG — in place; returns ``msg`` for chaining. The caller has
    already checked the peer granted FLAG_CAP_MUX. The tag goes OUTSIDE
    any trace-context prefix (obs/trace.attach runs first; receivers
    strip tag, then trace). A bulk payload becomes the vectored
    ``[tag, payload]`` form send_msg scatter-gathers — never a
    concatenating copy of the payload."""
    msg.flags |= FLAG_MUX_TAG
    head = _TAG.pack(tag)
    if isinstance(msg.data, (list, tuple)):
        msg.data = [head, *msg.data]
    elif len(msg.data) >= 4096:
        msg.data = [head, msg.data]
    else:
        msg.data = head + bytes(msg.data) if len(msg.data) else head
    return msg


def split_tag(data) -> tuple[int | None, object]:
    """Strip the u32 correlation id off a data tail. A tail shorter than
    the tag is malformed-but-tolerated (receivers must not die on a
    confused peer): returns (None, data) unchanged. The rest comes back
    as a VIEW (no payload copy — this runs per tagged frame on both
    sides); every consumer treats Message.data as a read-only buffer
    already."""
    if len(data) < TAG_BYTES:
        return None, data
    tag = _TAG.unpack_from(data, 0)[0]
    rest = (data if isinstance(data, memoryview)
            else memoryview(data))[TAG_BYTES:]
    return tag, rest


def pack_leader_tail(rank: int, host: str, port: int) -> bytes:
    """The NOT_MASTER redirect tail: current leader rank + address.
    Parsed back by :func:`remote_error` into ``leader_rank`` /
    ``leader_addr``; old peers ignore trailing data on ERROR frames."""
    return struct.pack("<q", rank) + _pack_str(host) + struct.pack("<I", port)


def request(sock: socket.socket, msg: Message) -> Message:
    """Send and await the reply (``send_recv_msg`` analogue, mem.c:63-88).
    An ERROR reply raises :class:`OcmRemoteError` — the connection stays in
    sync and reusable, unlike transport-level OcmProtocolError."""
    send_msg(sock, msg)
    reply = recv_msg(sock)
    if reply.type == MsgType.ERROR:
        raise remote_error(reply)
    return reply
