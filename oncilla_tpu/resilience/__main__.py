"""``python -m oncilla_tpu.resilience`` — chaos harness CLI.

``--smoke`` runs the canonical kill-the-owner scenario end to end,
TWICE, hardware-free, in-process:

  3-daemon local_cluster, OCM_REPLICAS=2, fast-detection config. A
  client writes half its data, then a seeded chaos schedule kills the
  owner daemon mid-workload (plus a couple of connection faults). The
  run asserts: every subsequent get() is byte-exact via the promoted
  replica, re-replication restores k=2 on a fresh rank, and — the
  determinism contract — the second run with the same seed injected the
  IDENTICAL fault interleaving (op-indexed chaos log compares equal).

``--plan`` prints the generated schedule for a seed without running
anything (what would be injected where).
"""

from __future__ import annotations

import argparse
import sys
import time

from oncilla_tpu.resilience.chaos import ChaosController, ChaosSchedule, Fault


def _scenario_schedule(seed: int, owner: int) -> ChaosSchedule:
    """Kill the owner early in the chaotic phase, with a dropped lease
    before it and a delayed one after — enough turbulence to exercise
    the retry ladder without drowning the log."""
    return ChaosSchedule.kill_at(
        seed, owner, op=4,
        extra=(
            Fault(op=2, action="drop"),
            Fault(op=7, action="delay", delay_s=0.002),
        ),
    )


def run_scenario(seed: int, verbose: bool = False) -> dict:
    """One full kill-owner-mid-workload run; returns the replay record
    (schedule + fired log + outcome) and raises on any failed check."""
    import numpy as np

    from oncilla_tpu.core.kinds import OcmKind
    from oncilla_tpu.runtime.cluster import local_cluster
    from oncilla_tpu.utils.config import OcmConfig

    cfg = OcmConfig(
        host_arena_bytes=32 << 20,
        device_arena_bytes=8 << 20,
        heartbeat_s=0.05,
        lease_s=5.0,
        replicas=2,
        detect_interval_s=0.05,
        suspect_after=1,
        dead_after=2,
        probe_timeout_s=0.25,
        dcn_stripes=2,
        dcn_stripe_min_bytes=1 << 20,
        chunk_bytes=256 << 10,
    )
    total = 4 << 20
    half = total // 2
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, total, dtype=np.uint8)
    with local_cluster(3, config=cfg) as cl:
        client = cl.client(0)
        h = client.alloc(total, OcmKind.REMOTE_HOST)
        assert h.replica_ranks, "OCM_REPLICAS=2 placement assigned no replica"
        owner = h.rank
        if verbose:
            print(f"  alloc {h.alloc_id}: primary rank {owner}, "
                  f"replicas {h.replica_ranks}")
        client.put(h, data[:half], 0)  # calm half

        schedule = _scenario_schedule(seed, owner)
        controller = ChaosController(schedule, cl.entries, kill_fn=cl.kill)
        with controller.inject():
            # Chaotic half: the kill fires at a fixed logical op index
            # while these puts (and the cluster's own background traffic)
            # drive the lease counter.
            step = 512 << 10
            for off in range(half, total, step):
                client.put(h, data[off:off + step], off)
            got = client.get(h, total)
        assert bytes(got) == data.tobytes(), (
            "get after owner kill is not byte-exact"
        )
        assert not controller.pending(), (
            f"workload too short for schedule: {controller.pending()}"
        )
        promoted = h.rank
        assert promoted != owner, "handle never failed over"

        # Re-replication restores k: the promoted primary's chain grows
        # back to 2 members, none of them the dead rank, and the fresh
        # copy is byte-exact.
        deadline = time.monotonic() + 20.0
        chain = ()
        while time.monotonic() < deadline:
            try:
                e = cl.daemons[promoted].registry.lookup(h.alloc_id)
            except Exception:  # noqa: BLE001 — registry churn mid-failover
                time.sleep(0.05)
                continue
            chain = e.chain
            if len(chain) >= 2 and owner not in chain:
                break
            time.sleep(0.05)
        assert len(chain) >= 2 and owner not in chain, (
            f"re-replication never restored k=2 (chain={chain})"
        )
        new_rep = next(r for r in chain if r != promoted)
        re = cl.daemons[new_rep].registry.lookup(h.alloc_id)
        rep_bytes = bytes(
            cl.daemons[new_rep].host_arena.view(re.extent)
        )[: re.nbytes]
        assert rep_bytes == data.tobytes(), (
            "re-replicated copy is not byte-exact"
        )
        got2 = client.get(h, total)
        assert bytes(got2) == data.tobytes()
        epoch = cl.daemons[0].epoch
        counters = dict(cl.daemons[0].res_counters)
    return {
        "seed": seed,
        "schedule": schedule,
        "log": list(controller.log),
        "owner": owner,
        "promoted": promoted,
        "chain": list(chain),
        "epoch": epoch,
        "counters": counters,
    }


def smoke(seed: int, verbose: bool = False) -> int:
    # Every run records under the flight recorder and must pass the
    # cross-rank invariant audit (obs/audit.py) — the timeline is
    # checked end to end, not just the end state. A finding raises with
    # the black-box path in the message.
    from oncilla_tpu.obs import audit as obs_audit

    print(f"resilience smoke: seed={seed} run 1/2 ...")
    with obs_audit.recorded("resilience-run1") as rec1:
        r1 = run_scenario(seed, verbose=verbose)
    print(f"  flight recorder: {rec1.summary()}")
    print(f"  owner rank {r1['owner']} killed -> promoted rank "
          f"{r1['promoted']}, chain restored to {r1['chain']}, "
          f"epoch {r1['epoch']}")
    print(f"  chaos log: {r1['log']}")
    print(f"resilience smoke: seed={seed} run 2/2 (replay) ...")
    with obs_audit.recorded("resilience-run2") as rec2:
        r2 = run_scenario(seed, verbose=verbose)
    print(f"  flight recorder: {rec2.summary()}")
    print(f"  chaos log: {r2['log']}")
    if r1["schedule"] != r2["schedule"]:
        print("resilience smoke: FAIL — schedules differ across runs")
        return 1
    if r1["log"] != r2["log"]:
        print("resilience smoke: FAIL — fault interleavings differ: "
              f"{r1['log']} vs {r2['log']}")
        return 1
    if (r1["owner"], r1["promoted"]) != (r2["owner"], r2["promoted"]):
        print("resilience smoke: FAIL — failover outcome differs")
        return 1
    print("resilience smoke: OK — kill-owner failover byte-exact, k "
          "restored, identical interleaving replayed, invariant audit "
          "clean on both timelines")
    return 0


def main(argv=None) -> int:
    from oncilla_tpu.utils.platform import honor_cpu_env

    honor_cpu_env()
    ap = argparse.ArgumentParser(
        prog="python -m oncilla_tpu.resilience",
        description="chaos/failover harness",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="run the kill-owner scenario twice and verify "
                         "byte-exact failover + deterministic replay")
    ap.add_argument("--plan", action="store_true",
                    help="print the generated random schedule for --seed")
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--nranks", type=int, default=3)
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    if args.plan:
        sched = ChaosSchedule.generate(
            args.seed, args.nranks,
            actions=("drop", "delay", "partition", "heal", "kill"),
        )
        for f in sched.faults:
            print(f"op {f.op:>4}: {f.action}"
                  + (f" rank {f.rank}" if f.rank >= 0 else "")
                  + (f" ({f.delay_s}s)" if f.action == "delay" else ""))
        return 0
    if args.smoke:
        return smoke(args.seed, verbose=args.verbose)
    ap.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
