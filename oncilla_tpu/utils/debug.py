"""Env-gated structured logging and op timing.

The reference's entire observability system is ``printd`` — print only when
``OCM_VERBOSE`` is set, prefixed with pid/tid/file/func/line
(/root/reference/inc/debug.h:22,50-65). This keeps the same env-var contract
but adds what SURVEY.md §5.1 calls for: per-op latency/bandwidth counters.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

_logger = logging.getLogger("oncilla_tpu")
if os.environ.get("OCM_VERBOSE"):
    logging.basicConfig(
        level=logging.DEBUG,
        format="%(asctime)s %(process)d/%(threadName)s %(name)s "
        "%(filename)s:%(lineno)d %(message)s",
    )
    _logger.setLevel(logging.DEBUG)


def printd(msg: str, *args) -> None:
    """Debug print, active only under ``OCM_VERBOSE`` (debug.h:22 contract)."""
    _logger.debug(msg, *args)


@dataclass
class OpStats:
    count: int = 0
    total_s: float = 0.0
    total_bytes: int = 0
    # Ring buffer: a deque with maxlen keeps the LATEST max_samples
    # latencies (a capped list kept only the oldest and froze p50 at the
    # warm-up distribution, and could overshoot the cap under races).
    samples_s: "deque[float]" = field(default_factory=deque)

    @property
    def p50_s(self) -> float:
        if not self.samples_s:
            return 0.0
        s = sorted(self.samples_s)
        return s[len(s) // 2]

    @property
    def gbps(self) -> float:
        return self.total_bytes / self.total_s / 1e9 if self.total_s else 0.0


class Tracer:
    """Per-op timing registry. ``tracer.span("put", nbytes=...)`` wraps an op;
    ``tracer.stats("put")`` reports count / p50 latency / GB/s."""

    def __init__(self, max_samples: int = 4096, max_transfers: int = 256):
        self._stats: dict[str, OpStats] = {}
        self._lock = threading.Lock()
        self._max_samples = max_samples
        # Per-transfer records of the DCN data plane (bytes, stripes,
        # window, achieved Gbps, retries) — the ring the STATUS endpoint
        # surfaces so operators see data-plane throughput without a
        # profiler attached.
        self._transfers: "deque[dict]" = deque(maxlen=max_transfers)

    def _get_locked(self, op: str) -> OpStats:
        st = self._stats.get(op)
        if st is None:
            st = self._stats[op] = OpStats(
                samples_s=deque(maxlen=self._max_samples)
            )
        return st

    @contextmanager
    def span(self, op: str, nbytes: int = 0):
        cls = _annotation_cls()
        annotation = cls(f"ocm:{op}") if cls is not None else None
        t0 = time.perf_counter()
        try:
            if annotation is None:
                yield
            else:
                with annotation:
                    yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                st = self._get_locked(op)
                st.count += 1
                st.total_s += dt
                st.total_bytes += nbytes
                st.samples_s.append(dt)  # deque(maxlen) evicts the oldest
            printd("op=%s nbytes=%d dt_us=%.1f", op, nbytes, dt * 1e6)

    def stats(self, op: str) -> OpStats:
        """A consistent SNAPSHOT of the op's stats: copied under the lock,
        so concurrent span() completions can't mutate the samples mid-sort
        in the caller's p50 computation."""
        with self._lock:
            st = self._get_locked(op)
            return OpStats(
                count=st.count,
                total_s=st.total_s,
                total_bytes=st.total_bytes,
                samples_s=deque(st.samples_s),
            )

    def note_transfer(
        self,
        op: str,
        nbytes: int,
        seconds: float,
        *,
        stripes: int = 1,
        window: int = 0,
        chunk_bytes: int = 0,
        retries: int = 0,
        coalesced: bool = False,
    ) -> None:
        """Record one completed data-plane transfer in the ring buffer."""
        rec = {
            "op": op,
            "bytes": int(nbytes),
            "seconds": seconds,
            "gbps": (nbytes * 8 / seconds / 1e9) if seconds > 0 else 0.0,
            "stripes": int(stripes),
            "window": int(window),
            "chunk_bytes": int(chunk_bytes),
            "retries": int(retries),
            "coalesced": bool(coalesced),
        }
        with self._lock:
            self._transfers.append(rec)

    def transfers(self, last: int | None = None) -> list[dict]:
        """Copies of the most recent transfer records (all by default)."""
        with self._lock:
            recs = list(self._transfers)
        return recs if last is None else recs[-last:]

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            return {
                k: {
                    "count": v.count,
                    "p50_us": v.p50_s * 1e6,
                    "gbps": v.gbps,
                    "total_bytes": v.total_bytes,
                }
                for k, v in self._stats.items()
            }


_ANNOTATION_CLS: object = False  # False = unresolved, None = unavailable


def _annotation_cls():
    """``jax.profiler.TraceAnnotation`` resolved once, so ocm op spans show
    up on the TensorBoard trace timeline; None when the profiler is
    unavailable (e.g. stripped minimal builds). Resolving per-span would put
    an import lookup inside every timed hot-path op."""
    global _ANNOTATION_CLS
    if _ANNOTATION_CLS is False:
        try:
            import jax.profiler

            _ANNOTATION_CLS = jax.profiler.TraceAnnotation
        except Exception:  # noqa: BLE001
            _ANNOTATION_CLS = None
    return _ANNOTATION_CLS


@contextmanager
def capture_trace(log_dir: str):
    """Capture a ``jax.profiler`` program trace around a block of ocm work::

        with capture_trace("/tmp/ocm-trace"):
            ctx.put(h, data)
            ctx.get(h)

    View with TensorBoard's profile plugin. Op spans recorded through
    ``Tracer.span`` appear as ``ocm:<op>`` annotations on the timeline.
    """
    import jax.profiler

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


GLOBAL_TRACER = Tracer()
