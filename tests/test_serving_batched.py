"""Batched serving engine — fused per-tick decode, chunked prefill,
admission-aware scheduling.

The correctness gate for the batched engine is *per-session byte
exactness* against the interleaved engine on the same seeded workload:
fusing sessions into one padded jit call, chunked prefill, priority
seating and budget-degraded faults are all scheduling/storage effects
and must never change a single emitted token. CPU-only (conftest pins
the backend); cluster-backed chaos legs live in ``python -m
oncilla_tpu.serving --smoke``.
"""

from __future__ import annotations

import numpy as np
import pytest

import oncilla_tpu as ocm
from oncilla_tpu.serving.metrics import ServingStats
from oncilla_tpu.serving.prefix import PrefixCache
from oncilla_tpu.serving.tiers import Tier, TieredPageStore

P = 8  # page_tokens for every engine in this file


@pytest.fixture(scope="module")
def tiny_model():
    from oncilla_tpu.models import LlamaConfig, init_params_host

    cfg = LlamaConfig.tiny()
    return cfg, init_params_host(0, cfg)


def build_engine(tiny_model, *, share=True, hot=3, warm=4, prefetch=0,
                 max_active=4, batched=True, max_batch=None,
                 step_budget_ms=None, name="t"):
    from oncilla_tpu.serving.engine import ServingEngine

    cfg, params = tiny_model
    pb = ServingEngine.page_nbytes(cfg, P)
    ctx = ocm.Ocm(config=ocm.OcmConfig(
        host_arena_bytes=1 << 20, device_arena_bytes=1 << 20,
    ))
    store = TieredPageStore(ctx, pb, hot_capacity=hot, warm_capacity=warm,
                            stats=ServingStats(name))
    prefix = PrefixCache(store, P) if share else None
    eng = ServingEngine(params, cfg, store, prefix, page_tokens=P,
                        max_active=max_active, prefetch_workers=prefetch,
                        name=name, batched=batched, max_batch=max_batch,
                        step_budget_ms=step_budget_ms)
    return ctx, store, eng


def run_prompts(tiny_model, prompts, *, new_tokens=6, priorities=None,
                **kw):
    from oncilla_tpu.serving.engine import Request

    ctx, store, eng = build_engine(tiny_model, **kw)
    try:
        for i, p in enumerate(prompts):
            req = Request(tenant=f"t{i}", tokens=list(p),
                          max_new_tokens=new_tokens)
            if priorities is not None:
                req.priority = priorities[i]
            eng.submit(req)
        results = eng.run()
        outs = {r.tenant: list(r.out_tokens) for r in results}
        order = [r.tenant for r in results]
        meta = eng.metrics_meta()
    finally:
        eng.close()
        store.close()
        ctx.tini()
    return outs, meta, order


def seeded_prompts(cfg, seed, *, n=4, shared=20, suffix=4):
    """Workload with a shared prefix, one identical pair (t0/t1), and
    per-tenant suffixes. ``shared + suffix`` page-aligned makes the
    pair's last page land in the CoW partial-adoption branch (the
    laggard adopts all-but-one token of the leader's final page by
    copy-on-write)."""
    rng = np.random.default_rng(seed)
    base = rng.integers(1, cfg.vocab, shared).tolist()
    p0 = base + rng.integers(1, cfg.vocab, suffix).tolist()
    prompts = [p0, list(p0)]
    for _ in range(n - 2):
        prompts.append(base + rng.integers(1, cfg.vocab, suffix).tolist())
    return prompts


# -- 1. paired byte-exactness through tier churn + CoW adoption ------------


def test_batched_matches_interleaved_through_churn_and_cow(tiny_model):
    cfg, _ = tiny_model
    prompts = seeded_prompts(cfg, 11, n=5, shared=20, suffix=4)
    # hot=2/warm=2 with 5 multi-page sessions forces continuous
    # demotion to the cold stand-in and promotion back (tier churn)
    # under BOTH engines; outputs must not notice.
    kw = dict(share=True, hot=2, warm=2, new_tokens=8, max_active=4)
    outs_il, meta_il, _ = run_prompts(tiny_model, prompts,
                                      batched=False, **kw)
    outs_b, meta_b, _ = run_prompts(tiny_model, prompts,
                                    batched=True, **kw)
    assert outs_b == outs_il
    # Identical prompts emitted identical continuations.
    assert outs_b["t0"] == outs_b["t1"]
    # The fused path actually ran (not a degenerate batch of one).
    assert meta_b["batch"]["steps"] > 0
    assert meta_b["batch"]["size_max"] >= 2
    # Tier churn engaged in the batched leg...
    assert meta_b["moves"]["demote"] > 0
    assert meta_b["moves"]["promote"] > 0
    # ...and so did prefix sharing with a CoW partial adoption
    # (the t0/t1 identical pair).
    assert meta_b["prefix"]["hits"] > 0
    assert meta_b["prefix"]["cow"] >= 1


# -- 2. chunked prefill ----------------------------------------------------


def test_chunked_prefill_admits_long_prompt_in_slices(tiny_model):
    cfg, _ = tiny_model
    rng = np.random.default_rng(23)
    long = rng.integers(1, cfg.vocab, 6 * P).tolist()  # 6-page prompt
    shorts = [rng.integers(1, cfg.vocab, 5).tolist() for _ in range(3)]
    prompts = [long] + shorts
    kw = dict(share=False, hot=6, warm=8, new_tokens=10, max_active=4)
    outs_il, meta_il, _ = run_prompts(tiny_model, prompts,
                                      batched=False, **kw)
    outs_b, meta_b, _ = run_prompts(tiny_model, prompts,
                                    batched=True, **kw)
    assert outs_b == outs_il
    b = meta_b["batch"]
    # The 6-page prompt admitted one page-sized slice per tick.
    assert b["prefill_chunks"] >= 6
    # The batch never stalled behind it: the short sessions kept
    # decoding every tick, so fused steps at least cover their decode
    # tokens and ran concurrently with the chunking ticks.
    assert b["steps"] >= kw["new_tokens"]
    assert b["size_max"] >= 2
    # Prefill tokens accounted exactly once each (chunked or batched):
    # every prompt token teacher-forced once, same total both engines.
    assert meta_b["tokens"]["prefill"] == sum(len(p) for p in prompts)
    assert meta_b["tokens"]["prefill"] == meta_il["tokens"]["prefill"]


# -- 3. admission-aware scheduler ------------------------------------------


def test_scheduler_prio_high_admitted_and_seated_first(tiny_model):
    from oncilla_tpu.qos.policy import PRIO_HIGH, PRIO_NORMAL

    cfg, _ = tiny_model
    rng = np.random.default_rng(31)
    prompts = [rng.integers(1, cfg.vocab, 6).tolist() for _ in range(4)]
    # The PRIO_HIGH request is submitted LAST but must be admitted (and
    # seated) first; max_batch=2 < max_active=4 forces slot contention
    # every tick, which the scheduler must resolve by priority.
    prios = [PRIO_NORMAL, PRIO_NORMAL, PRIO_NORMAL, PRIO_HIGH]
    kw = dict(share=False, new_tokens=6, max_active=4, max_batch=2)
    outs_b, meta_b, order = run_prompts(tiny_model, prompts,
                                        priorities=prios, batched=True,
                                        **kw)
    assert order[0] == "t3"  # the PRIO_HIGH tenant finished first
    assert meta_b["preempts"].get("slot", 0) >= 1
    # Priority is a scheduling effect only — outputs still match the
    # interleaved engine byte-for-byte.
    outs_il, _, _ = run_prompts(tiny_model, prompts, priorities=prios,
                                batched=False, share=False, new_tokens=6,
                                max_active=4)
    assert outs_b == outs_il


def test_scheduler_expired_budget_degrades_to_stall(tiny_model):
    import concurrent.futures as cf

    from oncilla_tpu.serving.engine import Request

    cfg, _ = tiny_model
    rng = np.random.default_rng(37)
    prompt = rng.integers(1, cfg.vocab, 2 * P).tolist()
    ctx, store, eng = build_engine(tiny_model, share=False, hot=4, warm=4,
                                  prefetch=2, batched=True,
                                  step_budget_ms=20)
    try:
        eng.submit(Request(tenant="t0", tokens=list(prompt),
                           max_new_tokens=4))
        # Prefill the prompt's two pages.
        while not eng.active or any(
                eng._bulk_prefill(s) for s in eng.active):
            eng._tick()
        sess = eng.active[0]
        page = sess.entries[0].page
        store.demote(page, Tier.WARM)
        # A prefetch that never lands: the next step's wait must expire
        # at the step budget and degrade to a synchronous fault with
        # the wait recorded as stall — never a wedged batch.
        eng.prefetcher._futures[page.page_id] = cf.Future()
        stalls0 = eng.stats.stalls
        eng._tick()
        assert eng.stats.stalls > stalls0
        assert eng.stats.stall_s > 0
        # The preempt ledger recorded the yielded seat before the
        # forced (budget-bounded) fault seated it anyway.
        assert eng.stats.preempts.get("cold_page", 0) >= 1
        results = eng.run()
        outs = {r.tenant: list(r.out_tokens) for r in results}
    finally:
        eng.close()
        store.close()
        ctx.tini()
    # Degradation is accounting-only: tokens match the clean run.
    clean, _, _ = run_prompts(tiny_model, [prompt], new_tokens=4,
                              share=False, hot=4, warm=4, batched=True)
    assert outs["t0"] == clean["t0"]


# -- 4. jit recompilations bounded by shape buckets ------------------------


def test_batched_recompilations_bounded_by_shape_buckets(tiny_model):
    from oncilla_tpu.models import paged_decode_batch_step_jit as kern

    cfg, _ = tiny_model
    rng = np.random.default_rng(41)
    # Heterogeneous batch sizes (1..5 live sessions as tenants finish)
    # and context lengths (1..4 pages) — hundreds of tokens through
    # the fused kernel.
    prompts = [rng.integers(1, cfg.vocab, ln).tolist()
               for ln in (5, 9, 17, 25, 30)]

    def workload():
        return run_prompts(tiny_model, prompts, new_tokens=12,
                           share=False, hot=8, warm=8, max_active=5,
                           batched=True)

    before = kern._cache_size()
    outs, meta, _ = workload()
    first = kern._cache_size() - before
    tokens = sum(len(o) for o in outs.values()) \
        + meta["tokens"]["prefill"]
    # Shape-bucketed padding keeps compiles O(log batch * log pages):
    # B buckets {1,2,4,8} x page buckets {1,2,4} — nowhere near the
    # token count.
    assert meta["batch"]["steps"] > 0
    assert 0 < first <= 8
    assert first < tokens / 10
    # A second identical workload hits the jit cache exactly.
    outs2, _, _ = workload()
    assert kern._cache_size() - before == first
    assert outs2 == outs
