// oncillamemd — the native per-host daemon for oncilla-tpu.
//
// Production C++ twin of the Python reference implementation in
// oncilla_tpu/runtime/daemon.py, speaking the identical wire protocol
// (protocol.hh). The analogue of the reference's bin/oncillamem
// (/root/reference/src/main.c + mem.c + alloc.c): an epoll-driven TCP
// server (per-connection frame state machines; a bounded worker pool
// serves the DATA plane, control messages keep their blocking semantics
// on per-message threads), rank-0 placement master (capacity-aware or
// neighbor round-robin), allocation registry with heartbeat-renewed
// leases (the liveness upgrade the reference left as a TODO,
// main.c:6-7), and the DCN data plane serving one-sided put/get into a
// daemon-owned host arena — with the v2 data-plane capabilities
// (FLAG_CAP_COALESCE ACK coalescing, zero-copy recv-into-arena DATA_PUT
// landings) the Python daemon grew in PR 3.
//
// Build: cmake -S . -B build && cmake --build build   (or: make)
// Run:   oncillamemd --nodefile FILE --rank N [flags]

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <fcntl.h>
#include <unistd.h>

#include <deque>

#include <algorithm>
#include <array>
#include <atomic>
#include <chrono>
#include <cstdlib>
#include <condition_variable>
#include <cerrno>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <iostream>
#include <memory>
#include <mutex>
#include <set>
#include <sstream>
#include <thread>
#include <vector>

#include "arena.hh"
#include "membership.hh"
#include "net.hh"
#include "obs.hh"
#include "protocol.hh"

namespace ocm {
namespace {

double now_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Serve-span op names for the types this daemon dispatches (the Python
// daemon's "srv_" + msg.type.name.lower(); data ops use the dcn_*_srv
// names the obs cluster table and Perfetto export already know).
const char* srv_op_name(MsgType t) {
  switch (t) {
    case MsgType::DATA_PUT: return "dcn_put_srv";
    case MsgType::DATA_GET: return "dcn_get_srv";
    case MsgType::CONNECT: return "srv_connect";
    case MsgType::DISCONNECT: return "srv_disconnect";
    case MsgType::ADD_NODE: return "srv_add_node";
    case MsgType::REQ_ALLOC: return "srv_req_alloc";
    case MsgType::DO_ALLOC: return "srv_do_alloc";
    case MsgType::REQ_FREE: return "srv_req_free";
    case MsgType::DO_FREE: return "srv_do_free";
    case MsgType::NOTE_FREE: return "srv_note_free";
    case MsgType::NOTE_ALLOC: return "srv_note_alloc";
    case MsgType::RECLAIM_APP: return "srv_reclaim_app";
    case MsgType::HEARTBEAT: return "srv_heartbeat";
    case MsgType::STATUS: return "srv_status";
    case MsgType::STATUS_PROM: return "srv_status_prom";
    case MsgType::STATUS_EVENTS: return "srv_status_events";
    case MsgType::PLANE_SERVE: return "srv_plane_serve";
    case MsgType::PLANE_PUT: return "srv_plane_put";
    case MsgType::PLANE_GET: return "srv_plane_get";
    case MsgType::PLANE_SCRUB: return "srv_plane_scrub";
    default: return "srv_msg";
  }
}

// Per-CONNECTION bulk-reply buffer pool. The epoll serve core hands a
// connection's messages to whichever worker is free, so a per-THREAD
// pool would interleave unrelated connections' reply buffers (and lose
// the reuse whenever a different worker picks the next chunk);
// per-connection pooling keeps the win — no fresh >=16 MiB allocation
// (mmap + first-touch page faults) per DATA_GET chunk — with ownership
// that matches the serve core's one-message-per-connection discipline.
// take_bulk_buffer hands the pooled capacity to a reply under
// construction; reclaim_bulk_buffer takes it back after the send.
std::vector<uint8_t> take_bulk_buffer(std::vector<uint8_t>& pool,
                                      const uint8_t* src, size_t n) {
  std::vector<uint8_t> buf;
  buf.swap(pool);
  // assign (not resize-then-copy): resize would value-initialize n bytes
  // only for the copy to overwrite them — a wasted full pass on the hot
  // path. assign reuses the pooled capacity and writes each byte once.
  buf.assign(src, src + n);
  return buf;
}

void reclaim_bulk_buffer(std::vector<uint8_t>& pool, Message& sent) {
  if (sent.data.capacity() > pool.capacity()) {
    sent.data.clear();
    pool.swap(sent.data);
  }
}

// Cached peer connections, no re-send on failure (pool.py semantics: control
// messages are not idempotent). Conns are shared_ptr-held: eviction/shutdown
// only ::shutdown()s the fd (waking any blocked recv) and drops the map
// reference; the fd is ::close()d by ~Conn when the last in-flight request
// lets go — so no thread ever uses a closed-and-reused fd number.
//
// MULTIPLE connections per peer (mirrors pool.py): one-conn-per-peer with
// its mutex held across the round-trip lets the waits-for graph cycle
// across >= 3 daemons (REQ_ALLOC forward + DO_ALLOC/DO_FREE legs +
// NOTE_FREE accounting) and deadlocks the cluster until socket timeouts.
// The message call graph is acyclic, so leasing an idle-or-fresh
// connection per request removes every mutex edge.
class PeerPool {
 public:
  Message request(const std::string& host, int port, const Message& m) {
    std::shared_ptr<Conn> c = lease(host, port);
    std::unique_lock<std::mutex> g(c->mu, std::adopt_lock);
    try {
      send_msg(c->fd, m);
      Message r = recv_msg(c->fd, &c->scratch);
      g.unlock();
      cv_.notify_all();  // a cap-blocked lease() can have this conn now
      return r;
    } catch (...) {
      // Any interrupted exchange leaves the stream desynced: evict the
      // connection (never cache a half-read one) and wake cap waiters,
      // since the peer's list just shrank below the bound.
      discard(host, port, c);
      g.unlock();
      cv_.notify_all();
      throw;
    }
  }

  // Terminal: refuses new dials afterwards, so a worker racing shutdown
  // cannot re-dial a hung peer and block stop()'s join forever.
  void close_all() {
    {
      std::lock_guard<std::mutex> g(mu_);
      closed_ = true;
      for (auto& kv : conns_)
        for (auto& c : kv.second) ::shutdown(c->fd, SHUT_RDWR);
      conns_.clear();
    }
    cv_.notify_all();  // cap-blocked leases must see closed_ and throw
  }

 private:
  struct Conn {
    int fd = -1;  // -1 until dial succeeds: ~Conn must never close(0)
    std::mutex mu;
    // Receive scratch reused across requests on this connection (the
    // holder of mu owns it; replies are consumed before the next recv).
    std::vector<uint8_t> scratch;
    ~Conn() {
      if (fd >= 0) ::close(fd);
    }
  };

  // Returns with c->mu HELD (caller adopts). Bounded at kPerPeer
  // connections per peer (pool.py's per_peer): at the cap, wait for any
  // in-flight request to that peer to finish instead of dialing without
  // bound under a concurrency spike.
  std::shared_ptr<Conn> lease(const std::string& host, int port) {
    auto key = host + ":" + std::to_string(port);
    {
      std::unique_lock<std::mutex> g(mu_);
      while (true) {
        if (closed_) throw ProtocolError("peer pool is shut down");
        auto& vec = conns_[key];
        for (auto& c : vec)
          if (c->mu.try_lock()) return c;
        if (vec.size() < kPerPeer) break;  // room: dial outside mu_
        // The timed wait is only a missed-notify backstop; request()'s
        // notify_all is the real wakeup.
        cv_.wait_for(g, std::chrono::seconds(1));
      }
    }
    auto c = std::make_shared<Conn>();
    c->fd = dial(host, port);
    c->mu.lock();
    std::lock_guard<std::mutex> g(mu_);
    if (closed_) {
      ::shutdown(c->fd, SHUT_RDWR);
      c->mu.unlock();
      throw ProtocolError("peer pool is shut down");
    }
    conns_[key].push_back(c);
    return c;
  }

  void discard(const std::string& host, int port,
               const std::shared_ptr<Conn>& c) {
    auto key = host + ":" + std::to_string(port);
    std::lock_guard<std::mutex> g(mu_);
    auto it = conns_.find(key);
    if (it == conns_.end()) return;
    auto& vec = it->second;
    for (auto vit = vec.begin(); vit != vec.end(); ++vit) {
      if (*vit == c) {
        ::shutdown(c->fd, SHUT_RDWR);
        vec.erase(vit);
        break;
      }
    }
  }

  static constexpr size_t kPerPeer = 16;  // pool.py per_peer
  std::mutex mu_;
  std::condition_variable cv_;
  bool closed_ = false;
  std::map<std::string, std::vector<std::shared_ptr<Conn>>> conns_;
};

// ---------------------------------------------------------------------------
// Membership, registry, placement.
// ---------------------------------------------------------------------------

struct RegEntry {
  uint64_t alloc_id;
  Kind kind;
  uint32_t device_index;
  Extent extent;
  uint64_t nbytes;
  int64_t origin_rank;
  int64_t origin_pid;
  double lease_expiry;
};

// Owner-side registry (registry.py twin): ids = (rank << 32) | (counter << 1).
class Registry {
 public:
  Registry(int64_t rank, double lease_s) : rank_(rank), lease_s_(lease_s) {}

  uint64_t next_id() {
    std::lock_guard<std::mutex> g(mu_);
    ++counter_;
    return (uint64_t(rank_) << 32) | (counter_ << 1);
  }

  void insert(RegEntry e) {
    std::lock_guard<std::mutex> g(mu_);
    entries_[e.alloc_id] = std::move(e);
  }

  RegEntry lookup(uint64_t id) const {
    std::lock_guard<std::mutex> g(mu_);
    auto it = entries_.find(id);
    if (it == entries_.end())
      throw BadHandleError("unknown alloc_id " + std::to_string(id));
    return it->second;
  }

  RegEntry remove(uint64_t id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = entries_.find(id);
    if (it == entries_.end())
      throw BadHandleError("unknown alloc_id " + std::to_string(id));
    RegEntry e = it->second;
    entries_.erase(it);
    return e;
  }

  void renew(int64_t pid, int64_t rank) {
    double deadline = now_s() + lease_s_;
    std::lock_guard<std::mutex> g(mu_);
    for (auto& kv : entries_)
      if (kv.second.origin_pid == pid && kv.second.origin_rank == rank)
        kv.second.lease_expiry = deadline;
  }

  std::vector<uint64_t> expired() const {
    double t = now_s();
    std::lock_guard<std::mutex> g(mu_);
    std::vector<uint64_t> out;
    for (auto& kv : entries_)
      if (kv.second.lease_expiry < t) out.push_back(kv.first);
    return out;
  }

  // Every allocation an app originated (disconnect-time reclamation — the
  // reference's unresolved TODO, main.c:6-7,58-103).
  std::vector<uint64_t> ids_for_app(int64_t pid, int64_t rank) const {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<uint64_t> out;
    for (auto& kv : entries_)
      if (kv.second.origin_pid == pid && kv.second.origin_rank == rank)
        out.push_back(kv.first);
    return out;
  }

  double new_deadline() const { return now_s() + lease_s_; }
  double lease_s() const { return lease_s_; }

  uint64_t live_count() const {
    std::lock_guard<std::mutex> g(mu_);
    return entries_.size();
  }

  uint64_t counter() const {
    std::lock_guard<std::mutex> g(mu_);
    return counter_;
  }

  void restore_counter(uint64_t v) {
    std::lock_guard<std::mutex> g(mu_);
    if (v > counter_) counter_ = v;
  }

  std::vector<RegEntry> all() const {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<RegEntry> out;
    for (auto& kv : entries_) out.push_back(kv.second);
    return out;
  }

 private:
  int64_t rank_;
  double lease_s_;
  mutable std::mutex mu_;
  uint64_t counter_ = 0;
  std::map<uint64_t, RegEntry> entries_;
};

struct NodeResources {
  int64_t rank;
  uint32_t ndevices;
  uint64_t device_arena_bytes;
  uint64_t host_arena_bytes;
  std::vector<uint64_t> device_used;
  uint64_t host_used = 0;
};

struct PlacementResult {
  int64_t rank;
  uint32_t device_index;
  Kind kind;
};

struct PlacementError : std::runtime_error {
  using std::runtime_error::runtime_error;
};

// Placement (placement.py twin): "capacity" = most-free-fit avoiding the
// origin; "neighbor" = (orig+1) % n reference parity (alloc.c:107).
class Placement {
 public:
  Placement(bool capacity_aware) : capacity_aware_(capacity_aware) {}

  void add_node(NodeResources r) {
    std::lock_guard<std::mutex> g(mu_);
    r.device_used.assign(r.ndevices, 0);
    nodes_[r.rank] = std::move(r);
  }

  int64_t nnodes() const {
    std::lock_guard<std::mutex> g(mu_);
    return int64_t(nodes_.size());
  }

  void note(Kind kind, int64_t rank, uint32_t dev, uint64_t nbytes, bool alloc) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = nodes_.find(rank);
    if (it == nodes_.end()) return;
    NodeResources& n = it->second;
    if (kind_is_host(kind)) {
      n.host_used = alloc ? n.host_used + nbytes
                          : (n.host_used > nbytes ? n.host_used - nbytes : 0);
    } else if (dev < n.device_used.size()) {
      uint64_t& u = n.device_used[dev];
      u = alloc ? u + nbytes : (u > nbytes ? u - nbytes : 0);
    }
  }

  PlacementResult place(int64_t orig_rank, Kind kind, uint64_t nbytes) {
    std::lock_guard<std::mutex> g(mu_);
    if (nodes_.empty()) throw PlacementError("no nodes registered");
    bool remote = kind == Kind::REMOTE_DEVICE || kind == Kind::REMOTE_HOST;
    if (nodes_.size() == 1 && remote) {
      // Single-node demotion (alloc.c:82-83).
      Kind demoted = kind == Kind::REMOTE_DEVICE ? Kind::LOCAL_DEVICE
                                                 : Kind::LOCAL_HOST;
      return {orig_rank, 0, demoted};
    }
    if (!capacity_aware_) {
      int64_t rank = (orig_rank + 1) % int64_t(nodes_.size());
      const NodeResources& n = nodes_.at(rank);
      if (kind == Kind::REMOTE_HOST) return {rank, 0, kind};
      rr_++;
      uint32_t dev = n.ndevices ? uint32_t(rr_ % n.ndevices) : 0;
      return {rank, dev, kind};
    }
    // Capacity-aware: most free bytes that fit, off-origin preferred.
    bool found = false;
    int64_t best_score = 0;
    PlacementResult best{0, 0, kind};
    for (auto& kv : nodes_) {
      const NodeResources& n = kv.second;
      int64_t pref = (kv.first != orig_rank) ? 0 : -(int64_t(1) << 62);
      if (kind == Kind::REMOTE_HOST) {
        int64_t freeb = int64_t(n.host_arena_bytes) - int64_t(n.host_used);
        if (freeb >= int64_t(nbytes)) {
          int64_t score = freeb + pref;
          if (!found || score > best_score) {
            found = true;
            best_score = score;
            best = {kv.first, 0, kind};
          }
        }
      } else {
        for (uint32_t d = 0; d < n.ndevices; ++d) {
          int64_t freeb =
              int64_t(n.device_arena_bytes) - int64_t(n.device_used[d]);
          if (freeb >= int64_t(nbytes)) {
            int64_t score = freeb + pref;
            if (!found || score > best_score) {
              found = true;
              best_score = score;
              best = {kv.first, d, kind};
            }
          }
        }
      }
    }
    if (!found)
      throw PlacementError("no node can fit " + std::to_string(nbytes) + " B");
    return best;
  }

 private:
  bool capacity_aware_;
  mutable std::mutex mu_;
  uint64_t rr_ = 0;
  std::map<int64_t, NodeResources> nodes_;
};

// ---------------------------------------------------------------------------
// The daemon.
// ---------------------------------------------------------------------------

struct Config {
  std::string nodefile;
  std::string snapshot_path;
  // Empty = bind the daemon's own nodefile hostname (routable to peers but
  // not the wildcard; the plane is unauthenticated, so INADDR_ANY is an
  // explicit opt-in via --bind-host 0.0.0.0 / OCM_BIND_HOST). Mirrors the
  // Python CLI (daemon.py main() passes host=entries[rank].host).
  std::string bind_host;
  int64_t rank = -1;
  bool capacity_policy = true;
  uint32_t ndevices = 1;
  uint64_t host_arena_bytes = 256ull << 20;
  uint64_t device_arena_bytes = 128ull << 20;
  uint64_t alignment = 4096;
  double lease_s = 30.0;
  double heartbeat_s = 5.0;
};

class Daemon {
 public:
  Daemon(const Config& cfg, std::vector<NodeEntry> entries)
      : cfg_(cfg),
        entries_(std::move(entries)),
        host_arena_(cfg.host_arena_bytes, cfg.alignment),
        host_store_(cfg.host_arena_bytes, 0),
        registry_(cfg.rank, cfg.lease_s),
        placement_(cfg.capacity_policy),
        track_("daemon-r" + std::to_string(cfg.rank)) {
    for (uint32_t i = 0; i < cfg.ndevices; ++i)
      device_books_.emplace_back(std::make_unique<ArenaAllocator>(
          cfg.device_arena_bytes, cfg.alignment));
    // OCM_NATIVE_OBS=0 reverts the daemon to its pre-obs surface: the
    // trace capability masked out of the CONNECT echo, STATUS_PROM /
    // STATUS_EVENTS answered with typed BAD_MSG, no journal, no
    // flight-recorder spill — what the obs CLI's graceful-degradation
    // path is regression-tested against.
    const char* nob = getenv("OCM_NATIVE_OBS");
    obs_enabled_ = !(nob != nullptr && std::string(nob) == "0");
    caps_mask_ = kFlagCapCoalesce | (obs_enabled_ ? kFlagCapTrace : 0);
  }

  void run() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr = {};
    addr.sin_family = AF_INET;
    if (cfg_.bind_host.empty())
      cfg_.bind_host = entries_[cfg_.rank].host;
    if (cfg_.bind_host == "0.0.0.0") {
      addr.sin_addr.s_addr = htonl(INADDR_ANY);
    } else if (inet_pton(AF_INET, cfg_.bind_host.c_str(), &addr.sin_addr) != 1) {
      // Not a dotted quad (e.g. a nodefile hostname): resolve it.
      addrinfo hints = {};
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      addrinfo* res = nullptr;
      if (getaddrinfo(cfg_.bind_host.c_str(), nullptr, &hints, &res) != 0 ||
          res == nullptr)
        throw std::runtime_error("cannot resolve bind host " + cfg_.bind_host);
      addr.sin_addr = ((sockaddr_in*)res->ai_addr)->sin_addr;
      freeaddrinfo(res);
    }
    addr.sin_port = htons(uint16_t(entries_[cfg_.rank].port));
    if (::bind(listen_fd_, (sockaddr*)&addr, sizeof(addr)) != 0)
      throw std::runtime_error("bind failed on port " +
                               std::to_string(entries_[cfg_.rank].port));
    ::listen(listen_fd_, 64);
    // The LISTEN fd is nonblocking so the event loop's accept drain never
    // parks; accepted connection fds stay BLOCKING (reads go through
    // FrameReader's MSG_DONTWAIT; replies ride the plain blocking
    // send_msg, woken by shutdown(2) at stop time).
    fcntl(listen_fd_, F_SETFL,
          fcntl(listen_fd_, F_GETFL, 0) | O_NONBLOCK);
    epoll_fd_ = ::epoll_create1(0);
    wake_fd_ = ::eventfd(0, EFD_NONBLOCK);
    if (epoll_fd_ < 0 || wake_fd_ < 0)
      throw std::runtime_error("epoll/eventfd setup failed");
    epoll_event ev = {};
    ev.events = EPOLLIN;
    ev.data.fd = listen_fd_;
    epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, listen_fd_, &ev);
    ev.data.fd = wake_fd_;
    epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, wake_fd_, &ev);
    running_ = true;

    if (cfg_.rank == 0) {
      placement_.add_node(own_resources());
    } else {
      notify_rank0();
    }
    maybe_restore();
    // Joined in stop(), never detached: a detached worker can wake after
    // run() returns and the Daemon is destroyed (use-after-free caught by
    // the TSan test). Started only after the fallible setup above — a throw
    // while a joinable thread is live would hit std::terminate in ~thread.
    reaper_thread_ = std::thread([this] {
      obs::set_thread_name("reaper");
      reaper_loop();
    });
    // Bounded DATA-plane worker pool: N concurrent stripe connections are
    // served by these few threads instead of N blocking ones. Control
    // messages never queue here (they may block on nested peer requests;
    // see handle_complete), so the pool can never deadlock on itself.
    size_t nworkers = kDefaultWorkers();
    if (const char* w = getenv("OCM_NATIVE_WORKERS")) {
      long v = std::atol(w);
      if (v >= 1 && v <= 64) nworkers = size_t(v);
    }
    for (size_t i = 0; i < nworkers; ++i)
      pool_threads_.emplace_back([this, i] {
        obs::set_thread_name("worker-" + std::to_string(i));
        worker_loop();
      });
    obs::set_thread_name("evloop");
    started_ok_ = true;
    std::printf("oncillamemd rank=%lld listening on %s:%d\n",
                (long long)cfg_.rank, entries_[cfg_.rank].host.c_str(),
                entries_[cfg_.rank].port);
    std::fflush(stdout);

    // The event loop: readiness only — per-connection frame assembly
    // happens in FrameReader, dispatch on workers/control threads.
    std::vector<epoll_event> events(64);
    while (running_) {
      int n = ::epoll_wait(epoll_fd_, events.data(), int(events.size()), -1);
      if (n < 0) {
        if (errno == EINTR) continue;
        break;
      }
      for (int i = 0; i < n && running_; ++i) {
        int fd = events[i].data.fd;
        if (fd == wake_fd_) {
          uint64_t tok;
          while (::read(wake_fd_, &tok, sizeof(tok)) > 0) {
          }
          continue;
        }
        if (fd == listen_fd_) {
          accept_ready();
          continue;
        }
        handle_readable(fd);
      }
    }
    stop();  // signal handler only requested; do the real teardown here
  }

  // Async-signal-safe: called from the SIGINT/SIGTERM handler. Only an
  // atomic store + eventfd write/shutdown(2); the real teardown (mutexes,
  // file I/O) happens on the main thread once epoll_wait returns.
  void request_stop() {
    signalled_.store(true);
    running_.store(false);
    if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
    if (wake_fd_ >= 0) {
      uint64_t one = 1;
      [[maybe_unused]] ssize_t r = ::write(wake_fd_, &one, sizeof(one));
    }
  }

  void stop() {
    // Black-box flush FIRST (the Python Daemon.kill() discipline): a
    // SIGTERM'd daemon — the closest observable analogue of a chaos
    // kill for an out-of-process rank — must leave its journal ring on
    // disk before teardown can hang on sockets or joins. Streamed
    // duplicates dedup away at merge time via (jid, seq), so the spill
    // can only ADD evidence. (A SIGKILL leaves no spill, but every
    // record was already streamed + flushed at record time.)
    if (jrec()) {
      if (signalled_.load())
        journal_.record("daemon_kill", track_,
                        obs::Fields().i("rank", cfg_.rank).str());
      journal_.spill_ring("kill-r" + std::to_string(cfg_.rank));
      journal_.flush();
    }
    running_ = false;
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    // Kick every serving thread off its socket before snapshotting: a
    // pool worker blocked in a reply send (stalled client) wakes with an
    // error once its fd is shut down.
    {
      std::lock_guard<std::mutex> g(conns_mu_);
      for (auto& kv : conns_) ::shutdown(kv.first, SHUT_RDWR);
    }
    // Unblock any worker waiting on a peer reply BEFORE joining — a hung
    // peer must not turn SIGTERM into an infinite hang (close_all also
    // refuses new dials from here on).
    peers_.close_all();
    // Drain the DATA-plane pool: stop flag + wakeup, then join.
    {
      std::lock_guard<std::mutex> g(queue_mu_);
      queue_stop_ = true;
    }
    queue_cv_.notify_all();
    for (std::thread& t : pool_threads_)
      if (t.joinable()) t.join();
    pool_threads_.clear();
    // Control threads exit promptly once their sockets/peers are shut
    // down; join them (and the reaper) so no thread can touch a
    // destroyed Daemon. Only the event loop spawns control threads and
    // it has exited by now. Joins run outside reap_mu_: an exiting
    // control thread takes that lock for its final finished_ push.
    std::vector<std::thread> leftover;
    {
      std::lock_guard<std::mutex> g(reap_mu_);
      leftover.swap(serve_threads_);
      finished_.clear();
    }
    for (std::thread& t : leftover)
      if (t.joinable()) t.join();
    if (reaper_thread_.joinable()) reaper_thread_.join();
    {
      std::lock_guard<std::mutex> g(conns_mu_);
      for (auto& kv : conns_) ::close(kv.first);
      conns_.clear();
    }
    if (epoll_fd_ >= 0) {
      ::close(epoll_fd_);
      epoll_fd_ = -1;
    }
    if (wake_fd_ >= 0) {
      ::close(wake_fd_);
      wake_fd_ = -1;
    }
    if (started_ok_) save_snapshot();
  }

 private:
  NodeResources own_resources() const {
    return {cfg_.rank, cfg_.ndevices, cfg_.device_arena_bytes,
            cfg_.host_arena_bytes, {}, 0};
  }

  void notify_rank0() {
    Message m{MsgType::ADD_NODE,
              {{"rank", Value::I(cfg_.rank)},
               {"host", Value::S(entries_[cfg_.rank].host)},
               {"port", Value::U(uint64_t(entries_[cfg_.rank].port))},
               {"ndevices", Value::U(cfg_.ndevices)},
               {"device_arena_bytes", Value::U(cfg_.device_arena_bytes)},
               {"host_arena_bytes", Value::U(cfg_.host_arena_bytes)}},
              {}};
    for (int attempt = 0; attempt < 40; ++attempt) {
      try {
        peers_.request(entries_[0].caddr(), entries_[0].port, m);
        return;
      } catch (const ProtocolError&) {
        std::this_thread::sleep_for(std::chrono::milliseconds(250));
      }
    }
    throw std::runtime_error("rank 0 daemon unreachable");
  }

  void reaper_loop() {
    // Lease reclamation (the reference's unresolved TODO, main.c:6-7).
    // Sleep in short slices so stop()'s join returns promptly.
    double slept = 0.0;
    while (running_) {
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
      reap_finished();
      slept += 0.05;
      if (slept < cfg_.heartbeat_s) continue;
      slept = 0.0;
      for (uint64_t id : registry_.expired()) {
        try {
          RegEntry e = registry_.lookup(id);
          do_free_local(id);
          lease_reclaims_.fetch_add(1, std::memory_order_relaxed);
          if (jrec())
            journal_.record("lease_reclaim", track_,
                            obs::Fields()
                                .u("alloc_id", e.alloc_id)
                                .u("nbytes", e.nbytes)
                                .i("origin_pid", e.origin_pid)
                                .i("origin_rank", e.origin_rank)
                                .str());
        } catch (const BadHandleError&) {
        }
      }
      bool pending;
      {
        std::lock_guard<std::mutex> g(plane_mu_);
        pending = !plane_unsynced_.empty();
      }
      if (pending) sync_plane_endpoint();
    }
  }

  // Per-connection serving state for the epoll core. Ownership is
  // exclusive at any instant: the event loop owns the connection while
  // assembling a frame (EPOLLONESHOT disarms it on delivery), then hands
  // it — message attached — to exactly one worker/control thread, which
  // re-arms it only after the reply is on the wire. `mu` makes each
  // handoff an explicit synchronization point; it is never contended.
  struct ServeConn {
    explicit ServeConn(int f) : fd(f) {}
    const int fd;
    FrameReader reader;  // event-loop-thread only
    std::mutex mu;       // held by the thread processing a message
    std::vector<uint8_t> bulk_buf;  // pooled DATA_GET_OK reply capacity
    // Coalesced-burst state (FLAG_MORE): per connection, so concurrent
    // stripes on sibling sockets never interact (daemon.py twin).
    uint64_t burst_nbytes = 0;
    bool burst_open = false;
    bool burst_err_set = false;
    Message burst_err;
  };

  static size_t kDefaultWorkers() {
    unsigned hw = std::thread::hardware_concurrency();
    return std::max(2u, std::min(8u, hw ? hw : 2u));
  }

  std::shared_ptr<ServeConn> conn_for(int fd) {
    std::lock_guard<std::mutex> g(conns_mu_);
    auto it = conns_.find(fd);
    return it == conns_.end() ? nullptr : it->second;
  }

  void accept_ready() {
    int one = 1;
    while (running_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;  // EAGAIN (drained) or shutdown
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      int buf = 4 << 20;  // stream 8 MiB chunks without window stalls
      setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
      setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
      {
        std::lock_guard<std::mutex> g(conns_mu_);
        conns_.emplace(fd, std::make_shared<ServeConn>(fd));
      }
      epoll_event ev = {};
      ev.events = EPOLLIN | EPOLLONESHOT;
      ev.data.fd = fd;
      epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, fd, &ev);
    }
  }

  // Re-arm a connection for its next frame (EPOLLONESHOT handoff back to
  // the event loop). Called by whichever thread finished the message.
  void rearm(int fd) {
    epoll_event ev = {};
    ev.events = EPOLLIN | EPOLLONESHOT;
    ev.data.fd = fd;
    epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, fd, &ev);
  }

  void close_conn(const std::shared_ptr<ServeConn>& c) {
    {
      std::lock_guard<std::mutex> g(conns_mu_);
      conns_.erase(c->fd);
    }
    epoll_ctl(epoll_fd_, EPOLL_CTL_DEL, c->fd, nullptr);
    ::close(c->fd);
  }

  // Event-loop read path: advance the connection's frame state machine.
  // DATA_PUT payloads that fully validate land STRAIGHT in the
  // destination arena extent via the router — the recv is the write.
  void handle_readable(int fd) {
    std::shared_ptr<ServeConn> c = conn_for(fd);
    if (c == nullptr) return;  // raced a close
    // Take the connection's ownership mutex for the read phase: the
    // previous message's worker released it only after its rearm, so
    // this acquire is the explicit happens-before edge for everything
    // that thread did on the connection (burst state, the fd itself) —
    // the epoll_ctl -> epoll_wait edge alone is invisible to older
    // TSan runtimes. Never contended: EPOLLONESHOT guarantees the fd
    // has no event in flight while a worker owns it.
    std::lock_guard<std::mutex> own(c->mu);
    FrameReader::Status st;
    try {
      st = c->reader.advance(fd, [this](Message& m, size_t n) {
        return route_put_payload(m, n);
      });
    } catch (const ProtocolError& e) {
      // Malformed wire input, truncation, a reset from a crashed peer —
      // worth a diagnostic saying which (daemon.py twin).
      if (getenv("OCM_VERBOSE"))
        std::fprintf(stderr, "oncillamemd: dropping conn: %s\n", e.what());
      close_conn(c);
      return;
    }
    if (st == FrameReader::Status::kNeedMore) {
      rearm(fd);
      return;
    }
    if (st == FrameReader::Status::kClosed) {
      close_conn(c);  // clean close at a frame boundary: normal
      return;
    }
    Message msg;
    try {
      msg = c->reader.take();
    } catch (const UnknownMsgError& e) {
      // A type this build predates (elastic membership & co): the frame
      // was fully consumed, the stream is in sync — decline the family
      // with a typed BAD_MSG and keep serving, exactly how an
      // un-upgraded v2 Python peer answers. The reply rides the pool
      // (no dispatch, nothing to block on).
      enqueue_work(c, Message{}, e.what());
      return;
    } catch (const ProtocolError& e) {
      if (getenv("OCM_VERBOSE"))
        std::fprintf(stderr, "oncillamemd: dropping conn: %s\n", e.what());
      close_conn(c);
      return;
    }
    handle_complete(c, std::move(msg));
  }

  // Route a completed message: DATA-plane ops ride the bounded worker
  // pool (their dispatch never issues a daemon-to-daemon request that
  // could wait on another pool, so the pool cannot deadlock on itself);
  // everything else — the control plane, PLANE_* relays — keeps its
  // blocking semantics on a per-message thread, the finer-grained twin
  // of the old thread-per-connection serve loop (nested peer legs like
  // REQ_FREE -> DO_FREE -> NOTE_FREE must never compete with stripe
  // traffic for pool slots).
  void handle_complete(const std::shared_ptr<ServeConn>& c, Message msg) {
    if (msg.type == MsgType::DATA_PUT || msg.type == MsgType::DATA_GET) {
      enqueue_work(c, std::move(msg), nullptr);
      return;
    }
    std::lock_guard<std::mutex> g(reap_mu_);
    serve_threads_.emplace_back(
        [this, c, m = std::move(msg)]() mutable {
          process_message(c, std::move(m), nullptr);
          std::lock_guard<std::mutex> g2(reap_mu_);
          finished_.push_back(std::this_thread::get_id());
        });
  }

  struct Work {
    std::shared_ptr<ServeConn> conn;
    Message msg;
    bool is_unknown = false;   // answer BAD_MSG(unknown_detail), no dispatch
    std::string unknown_detail;
  };

  void enqueue_work(const std::shared_ptr<ServeConn>& c, Message msg,
                    const char* unknown_detail) {
    Work w;
    w.conn = c;
    w.msg = std::move(msg);
    if (unknown_detail != nullptr) {
      w.is_unknown = true;
      w.unknown_detail = unknown_detail;
    }
    {
      std::lock_guard<std::mutex> g(queue_mu_);
      queue_.push_back(std::move(w));
    }
    queue_cv_.notify_one();
  }

  void worker_loop() {
    while (true) {
      Work w;
      {
        std::unique_lock<std::mutex> g(queue_mu_);
        queue_cv_.wait(g, [this] { return queue_stop_ || !queue_.empty(); });
        if (queue_stop_ && queue_.empty()) return;
        w = std::move(queue_.front());
        queue_.pop_front();
      }
      process_message(w.conn, std::move(w.msg),
                      w.is_unknown ? w.unknown_detail.c_str() : nullptr);
    }
  }

  // Dispatch + reply for one message, on whichever thread owns the
  // connection right now. Implements the ACK-coalescing contract
  // (daemon.py _serve_conn twin): a DATA_PUT carrying FLAG_MORE is a
  // non-final chunk of a burst — applied but NOT answered; the first
  // chunk without the bit closes the burst and gets ONE reply covering
  // all of it (total bytes on success, the burst's first ERROR
  // otherwise). Replies stay FIFO per connection; there are simply
  // fewer of them.
  void process_message(const std::shared_ptr<ServeConn>& c, Message msg,
                       const char* unknown_detail) {
    std::lock_guard<std::mutex> own(c->mu);
    Message reply;
    bool is_put = false;
    if (unknown_detail != nullptr) {
      reply = err(ErrCode::BAD_MSG, unknown_detail);
    } else {
      is_put = msg.type == MsgType::DATA_PUT;
      if (c->burst_open && !is_put) {
        // A sender may not interleave other requests inside an
        // unfinished burst — the reply stream would desync.
        c->burst_open = false;
        c->burst_err_set = false;
        c->burst_nbytes = 0;
        reply = err(ErrCode::BAD_MSG,
                    "request inside an open DATA_PUT burst");
      } else {
        // Serve-side spans (daemon.py _serve_conn twin): data ops are
        // always measured; control ops get a span only when the request
        // carried a trace context, so the exported trace shows the
        // daemon hop, not just the client's view of the round-trip.
        bool data_op = is_put || msg.type == MsgType::DATA_GET;
        bool spanned = obs_enabled_ && (data_op || msg.trace_id != 0);
        uint64_t span_nbytes =
            data_op && msg.fields.count("nbytes") ? msg.u("nbytes") : 0;
        double wall0 = spanned ? obs::wall_s() : 0.0;
        double t0 = spanned ? obs::mono_s() : 0.0;
        try {
          reply = dispatch(*c, msg);
        } catch (const OomError& e) {
          reply = err(ErrCode::OOM, e.what());
        } catch (const BoundsError& e) {
          reply = err(ErrCode::BOUNDS, e.what());
        } catch (const BadHandleError& e) {
          reply = err(ErrCode::BAD_ALLOC_ID, e.what());
        } catch (const PlacementError& e) {
          reply = err(ErrCode::PLACEMENT, e.what());
        } catch (const std::exception& e) {
          reply = err(ErrCode::UNKNOWN, e.what());
        }
        if (spanned)
          record_span(srv_op_name(msg.type), wall0, obs::mono_s() - t0,
                      span_nbytes, msg);
      }
    }
    bool more = is_put && (msg.flags & kFlagMore) != 0;
    if (is_put && (more || c->burst_open)) {
      if (!c->burst_open) c->burst_open = true;
      if (reply.type == MsgType::ERR) {
        if (!c->burst_err_set) {
          c->burst_err = reply;
          c->burst_err_set = true;
        }
      } else {
        c->burst_nbytes += reply.u("nbytes");
      }
      if (more) {
        rearm(c->fd);  // reply deferred to the burst's last chunk
        return;
      }
      reply = c->burst_err_set
                  ? c->burst_err
                  : Message{MsgType::DATA_PUT_OK,
                            {{"nbytes", Value::U(c->burst_nbytes)}},
                            {}};
      c->burst_open = false;
      c->burst_err_set = false;
      c->burst_nbytes = 0;
    }
    try {
      send_msg(c->fd, reply);
    } catch (const ProtocolError&) {
      close_conn(c);
      return;
    }
    // Hand a sent bulk reply's buffer back to this CONNECTION's pool so
    // its next DATA_GET reuses the capacity: a FRESH vector per 16 MiB
    // reply goes through mmap + first-touch page faults + copy, which
    // measured as ~40% of the GET leg's loopback bandwidth. (A pointer
    // view into the arena would avoid the copy too, but it would extend
    // the freed-extent race across a potentially stalled send — the
    // snapshot copy keeps that window bounded to dispatch.)
    reclaim_bulk_buffer(c->bulk_buf, reply);
    rearm(c->fd);
  }

  // Zero-copy DATA_PUT landing (daemon.py _route_put_payload twin): only
  // a chunk that fully validates routes; anything questionable returns
  // nullptr and takes the copy path, where the handler raises the typed
  // error. TOCTOU note: a concurrent free could recycle the extent
  // between this lookup and the recv completing — the same class of
  // window the copy path already has, reachable only by an app freeing
  // an allocation while actively writing it; the handler revalidates
  // after the recv and answers BAD_ALLOC_ID so such a writer cannot
  // treat the landing as durable.
  uint8_t* route_put_payload(Message& m, size_t n_data) {
    if (m.type != MsgType::DATA_PUT) return nullptr;
    try {
      uint64_t off = m.u("offset");
      uint64_t n = m.u("nbytes");
      if (n != n_data) return nullptr;
      RegEntry e = registry_.lookup(m.u("alloc_id"));
      if (!kind_is_host(e.kind)) return nullptr;  // device relay needs
                                                  // the payload in-frame
      if (off + n > e.nbytes || off + n < off) return nullptr;
      return host_store_.data() + e.extent.offset + off;
    } catch (const std::exception&) {
      return nullptr;
    }
  }

  // Join control threads that have finished (their stacks are not
  // reclaimed until joined). Runs from the reaper loop so idle daemons
  // reclaim too, not just ones with a steady stream of new messages.
  // Joins happen outside reap_mu_ — the exiting thread's own final push
  // needs that lock.
  void reap_finished() {
    std::vector<std::thread> done;
    {
      std::lock_guard<std::mutex> g(reap_mu_);
      for (std::thread::id id : finished_)
        for (auto it = serve_threads_.begin(); it != serve_threads_.end(); ++it)
          if (it->get_id() == id) {
            done.push_back(std::move(*it));
            serve_threads_.erase(it);
            break;
          }
      finished_.clear();
    }
    for (std::thread& t : done) t.join();
  }

  static Message err(ErrCode c, const std::string& detail) {
    return {MsgType::ERR,
            {{"code", Value::U(uint64_t(c))}, {"detail", Value::S(detail)}},
            {}};
  }

  // Journaling is on only when the obs surface is enabled AND the
  // process opted in (OCM_EVENTS / OCM_FLIGHTREC) — the same gate
  // journal.py applies, so the disarmed daemon does zero extra work.
  bool jrec() const { return obs_enabled_ && journal_.enabled(); }

  void record_span(const char* op, double wall0, double dt_s,
                   uint64_t nbytes, const Message& m) {
    opstats_.note(op, dt_s, nbytes);
    if (!jrec()) return;
    obs::Fields f;
    f.s("op", op).u("nbytes", nbytes).d("t_wall", wall0)
        .d("dur_us", dt_s * 1e6)
        .u("trace_id", m.trace_id)
        .u("span_id", m.trace_id ? obs::rand_id() : 0)
        .u("parent_span_id", m.trace_span_id);
    journal_.record("span", track_, f.str());
  }

  Message dispatch(ServeConn& c, const Message& m) {
    switch (m.type) {
      case MsgType::DISCONNECT:
        on_disconnect(m);
        [[fallthrough]];
      case MsgType::CONNECT: {
        Message confirm{MsgType::CONNECT_CONFIRM,
                        {{"rank", Value::I(cfg_.rank)},
                         {"nnodes", Value::I(cfg_.rank == 0
                                                 ? placement_.nnodes()
                                                 : int64_t(entries_.size()))}},
                        {}};
        // Capability negotiation (protocol.py FLAG_CAP_* contract): echo
        // exactly the offered bits this daemon implements — ACK
        // coalescing and (unless OCM_NATIVE_OBS=0) trace propagation.
        // Every other offer (replica, qos, fabric, and any QoS profile
        // data tail riding the frame) is declined by silence: masked
        // out of the echo, tail ignored, so un-upgraded clients and
        // capability-rich ones both get exactly the protocol they can
        // speak (pinned by the declined-by-silence tests).
        if (m.type == MsgType::CONNECT)
          confirm.flags = m.flags & caps_mask_;
        return confirm;
      }
      case MsgType::RECLAIM_APP:
        return {MsgType::RECLAIM_APP_OK,
                {{"count",
                  Value::U(reclaim_app_local(m.i("pid"), m.i("rank")))}},
                {}};
      case MsgType::ADD_NODE: return on_add_node(m);
      case MsgType::REQ_ALLOC: return on_req_alloc(m);
      case MsgType::DO_ALLOC: return on_do_alloc(m);
      case MsgType::REQ_FREE: return on_req_free(m);
      case MsgType::DO_FREE:
        do_free_local(m.u("alloc_id"));
        return {MsgType::FREE_OK, {{"alloc_id", Value::U(m.u("alloc_id"))}}, {}};
      case MsgType::NOTE_FREE: return on_note_free(m);
      case MsgType::NOTE_ALLOC: return on_note_alloc(m);
      case MsgType::DATA_PUT: return on_data_put(m);
      case MsgType::DATA_GET: return on_data_get(c, m);
      case MsgType::PLANE_SERVE: return on_plane_serve(m);
      case MsgType::PLANE_PUT: return forward_to_plane(m);
      case MsgType::PLANE_GET: return forward_to_plane(m);
      case MsgType::PLANE_SCRUB: return forward_to_plane(m);
      case MsgType::HEARTBEAT: return on_heartbeat(m);
      case MsgType::STATUS: return on_status();
      case MsgType::STATUS_PROM:
        if (!obs_enabled_) break;  // OCM_NATIVE_OBS=0: pre-obs surface
        return on_status_prom();
      case MsgType::STATUS_EVENTS:
        if (!obs_enabled_) break;
        return on_status_events();
      default:
        break;
    }
    return err(ErrCode::BAD_MSG, "unhandled message type");
  }

  Message on_add_node(const Message& m) {
    if (cfg_.rank != 0) return err(ErrCode::NOT_MASTER, "ADD_NODE to non-master");
    NodeResources r{m.i("rank"), uint32_t(m.u("ndevices")),
                    m.u("device_arena_bytes"), m.u("host_arena_bytes"), {}, 0};
    placement_.add_node(std::move(r));
    int64_t rank = m.i("rank");
    if (rank >= 0 && size_t(rank) < entries_.size()) {
      {
        std::lock_guard<std::mutex> g(entries_mu_);
        entries_[rank] = {rank, m.s("host"), int(m.u("port")),
                          entries_[rank].addr};
      }
      // A (re)joining daemon holds no plane endpoint: queue it for the
      // reaper's gossip — AFTER the entries update so the gossip dials
      // the replacement's address, never the dead predecessor's, and
      // only for in-range ranks (an out-of-range one would throw in the
      // reaper every tick and never be erased). daemon.py twin.
      std::lock_guard<std::mutex> g(plane_mu_);
      if (!plane_host_.empty()) plane_unsynced_.insert(rank);
    }
    return {MsgType::ADD_NODE_OK, {{"nnodes", Value::I(placement_.nnodes())}}, {}};
  }

  Message on_req_alloc(const Message& m) {
    if (cfg_.rank != 0) {
      // Proxy the whole request to the master (the placement leg,
      // mem.c:128).
      NodeEntry r0 = entry(0);
      return peers_.request(r0.caddr(), r0.port, m);
    }
    Kind kind = Kind(uint8_t(m.u("kind")));
    uint64_t nbytes = m.u("nbytes");
    PlacementResult placed = placement_.place(m.i("orig_rank"), kind, nbytes);
    NodeEntry owner = entry(placed.rank);
    uint64_t alloc_id, offset;
    if (placed.rank == cfg_.rank) {
      do_alloc_local(placed.kind, placed.device_index, nbytes,
                     m.i("orig_rank"), m.i("pid"), &alloc_id, &offset);
    } else {
      Message r = peers_.request(
          owner.caddr(), owner.port,
          {MsgType::DO_ALLOC,
           {{"orig_rank", Value::I(m.i("orig_rank"))},
            {"pid", Value::I(m.i("pid"))},
            {"kind", Value::U(uint64_t(placed.kind))},
            {"device_index", Value::U(placed.device_index)},
            {"nbytes", Value::U(nbytes)}},
           {}});
      if (r.type == MsgType::ERR) return r;
      alloc_id = r.u("alloc_id");
      offset = r.u("offset");
    }
    placement_.note(placed.kind, placed.rank, placed.device_index, nbytes,
                    /*alloc=*/true);
    return {MsgType::ALLOC_RESULT,
            {{"alloc_id", Value::U(alloc_id)},
             {"rank", Value::I(placed.rank)},
             {"device_index", Value::U(placed.device_index)},
             {"kind", Value::U(uint64_t(placed.kind))},
             {"offset", Value::U(offset)},
             {"nbytes", Value::U(nbytes)},
             {"owner_host", Value::S(owner.caddr())},
             {"owner_port", Value::U(uint64_t(owner.port))}},
            {}};
  }

  Message on_do_alloc(const Message& m) {
    uint64_t alloc_id, offset;
    do_alloc_local(Kind(uint8_t(m.u("kind"))), uint32_t(m.u("device_index")),
                   m.u("nbytes"), m.i("orig_rank"), m.i("pid"), &alloc_id,
                   &offset);
    return {MsgType::DO_ALLOC_OK,
            {{"alloc_id", Value::U(alloc_id)}, {"offset", Value::U(offset)}},
            {}};
  }

  // alloc_ate analogue (alloc.c:151-222): reserve BEFORE replying (fixes the
  // reference's reply-before-listen race, mem.c:350-354).
  void do_alloc_local(Kind kind, uint32_t device_index, uint64_t nbytes,
                      int64_t orig_rank, int64_t pid, uint64_t* alloc_id,
                      uint64_t* offset) {
    Extent ext;
    if (kind_is_host(kind)) {
      ext = host_arena_.alloc(nbytes);
      device_index = 0;
    } else {
      if (device_index >= device_books_.size())
        throw BadHandleError("bad device_index");
      ext = device_books_[device_index]->alloc(nbytes);
    }
    *alloc_id = registry_.next_id();
    *offset = ext.offset;
    registry_.insert({*alloc_id, kind, device_index, ext, nbytes, orig_rank,
                      pid, registry_.new_deadline()});
  }

  Message on_req_free(const Message& m) {
    int64_t owner_rank = m.i("rank");
    if (owner_rank < 0 || size_t(owner_rank) >= entries_.size())
      throw BadHandleError("bad owner rank " + std::to_string(owner_rank));
    if (owner_rank == cfg_.rank) {
      do_free_local(m.u("alloc_id"));
    } else {
      NodeEntry owner = entry(owner_rank);
      Message r = peers_.request(
          owner.caddr(), owner.port,
          {MsgType::DO_FREE, {{"alloc_id", Value::U(m.u("alloc_id"))}}, {}});
      if (r.type == MsgType::ERR) return r;
    }
    return {MsgType::FREE_OK, {{"alloc_id", Value::U(m.u("alloc_id"))}}, {}};
  }

  // dealloc_ate analogue (alloc.c:231-282), plus the rank-0 accounting the
  // reference stubbed (mem.c:221-229).
  void do_free_local(uint64_t alloc_id) {
    RegEntry e = registry_.remove(alloc_id);
    if (kind_is_host(e.kind)) {
      // Scrub on free (reference parity: server buffers are calloc'd,
      // alloc.c:171): the next tenant of this extent reads zeros.
      std::memset(host_store_.data() + e.extent.offset, 0, e.extent.nbytes);
      host_arena_.release(e.extent.offset);
    } else {
      // Device twin of the host scrub: ask the plane controller to zero
      // the extent BEFORE the offset returns to the book (O(1) wire).
      // Skipped unless this daemon knows a plane endpoint or has relayed
      // a device write — a bookkeeping-only workload must not pay a
      // master round trip per free (daemon.py twin).
      bool known;
      {
        std::lock_guard<std::mutex> g(plane_mu_);
        known = !plane_host_.empty();
      }
      if (known || device_writes_relayed_) {
        try {
          forward_to_plane(Message{
              MsgType::PLANE_SCRUB,
              {{"alloc_id", Value::U(e.alloc_id)},
               {"rank", Value::I(cfg_.rank)},
               {"device_index", Value::U(e.device_index)},
               {"ext_offset", Value::U(e.extent.offset)},
               {"ext_nbytes", Value::U(e.nbytes)}},
              {}});
        } catch (const std::exception&) {
        }
      }
      device_books_[e.device_index]->release(e.extent.offset);
    }
    if (jrec())
      journal_.record("free_local", track_,
                      obs::Fields()
                          .u("alloc_id", e.alloc_id)
                          .u("nbytes", e.nbytes)
                          .i("origin_pid", e.origin_pid)
                          .i("origin_rank", e.origin_rank)
                          .b("migrating", false)
                          .str());
    Message note{MsgType::NOTE_FREE,
                 {{"kind", Value::U(uint64_t(e.kind))},
                  {"rank", Value::I(cfg_.rank)},
                  {"device_index", Value::U(e.device_index)},
                  {"nbytes", Value::U(e.nbytes)}},
                 {}};
    if (cfg_.rank == 0) {
      on_note_free(note);
    } else {
      try {
        NodeEntry r0 = entry(0);
        peers_.request(r0.caddr(), r0.port, note);
      } catch (const ProtocolError&) {
      }
    }
  }

  Message on_note_free(const Message& m) {
    if (cfg_.rank == 0)
      placement_.note(Kind(uint8_t(m.u("kind"))), m.i("rank"),
                      uint32_t(m.u("device_index")), m.u("nbytes"),
                      /*alloc=*/false);
    return {MsgType::FREE_OK, {{"alloc_id", Value::U(0)}}, {}};
  }

  Message on_note_alloc(const Message& m) {
    if (cfg_.rank == 0)
      placement_.note(Kind(uint8_t(m.u("kind"))), m.i("rank"),
                      uint32_t(m.u("device_index")), m.u("nbytes"),
                      /*alloc=*/true);
    return {MsgType::FREE_OK, {{"alloc_id", Value::U(0)}}, {}};
  }

  // -- checkpoint / resume (snapshot.py's binary format, interchangeable
  // with the Python daemon's snapshots) ----------------------------------

  void save_snapshot() {
    if (cfg_.snapshot_path.empty()) return;
    std::string tmp = cfg_.snapshot_path + ".tmp";
    int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) {
      std::fprintf(stderr, "oncillamemd: snapshot open failed: %s\n",
                   std::strerror(errno));
      return;
    }
    uint32_t crc = 0;  // v2 trailer accumulates over every written byte
    auto write_all = [&](const uint8_t* p, size_t n) {
      crc = crc32_update(crc, p, n);
      size_t done = 0;
      while (done < n) {
        ssize_t w = ::write(fd, p + done, n - done);
        if (w <= 0) return false;
        done += size_t(w);
      }
      return true;
    };
    // Live arena bytes are written straight from host_store_, entry by
    // entry, so peak memory overhead is one metadata record — not a full
    // copy of every live byte (which could double resident memory on a
    // mostly-full arena at shutdown).
    std::vector<uint8_t> rec;
    auto put_le = [&](uint64_t v, int n) {
      for (int i = 0; i < n; ++i) rec.push_back((v >> (8 * i)) & 0xff);
    };
    bool ok = true;
    rec.insert(rec.end(), {'O', 'C', 'M', 'S'});
    rec.push_back(2);  // snapshot version (v2: CRC32 trailer)
    put_le(uint64_t(cfg_.rank), 8);
    put_le(registry_.counter(), 8);
    auto entries = registry_.all();
    put_le(entries.size(), 4);
    ok = write_all(rec.data(), rec.size());
    for (const RegEntry& e : entries) {
      if (!ok) break;
      rec.clear();
      put_le(e.alloc_id, 8);
      rec.push_back(uint8_t(e.kind));
      put_le(e.device_index, 4);
      put_le(e.extent.offset, 8);
      put_le(e.nbytes, 8);
      put_le(uint64_t(e.origin_rank), 8);
      put_le(uint64_t(e.origin_pid), 8);
      put_le(kind_is_host(e.kind) ? e.nbytes : 0, 8);
      ok = write_all(rec.data(), rec.size());
      if (ok && kind_is_host(e.kind))
        ok = write_all(host_store_.data() + e.extent.offset, e.nbytes);
    }
    if (ok) {
      // Trailer bytes are NOT fed back into the accumulator.
      uint8_t tail[4] = {uint8_t(crc & 0xff), uint8_t((crc >> 8) & 0xff),
                         uint8_t((crc >> 16) & 0xff),
                         uint8_t((crc >> 24) & 0xff)};
      uint32_t keep = crc;
      ok = write_all(tail, 4);
      crc = keep;
    }
    if (!ok) {
      std::fprintf(stderr, "oncillamemd: snapshot write failed: %s\n",
                   std::strerror(errno));
      ::close(fd);
      ::unlink(tmp.c_str());  // never rename a bad snapshot into place
      return;
    }
    if (::fsync(fd) != 0 || ::close(fd) != 0 ||
        ::rename(tmp.c_str(), cfg_.snapshot_path.c_str()) != 0) {
      std::fprintf(stderr, "oncillamemd: snapshot finalize failed: %s\n",
                   std::strerror(errno));
      ::unlink(tmp.c_str());
    }
  }

  void maybe_restore() {
    if (cfg_.snapshot_path.empty()) return;
    std::ifstream f(cfg_.snapshot_path, std::ios::binary);
    if (!f) return;
    std::vector<uint8_t> raw((std::istreambuf_iterator<char>(f)),
                             std::istreambuf_iterator<char>());
    size_t off = 0;
    auto get_le = [&](int n) -> uint64_t {
      if (off + n > raw.size()) throw ProtocolError("truncated snapshot");
      uint64_t v = 0;
      for (int i = 0; i < n; ++i) v |= uint64_t(raw[off + i]) << (8 * i);
      off += n;
      return v;
    };
    if (raw.size() < 5 || std::memcmp(raw.data(), "OCMS", 4) != 0)
      throw ProtocolError("bad snapshot magic");
    off = 4;
    uint64_t version = get_le(1);
    if (version != 1 && version != 2)
      throw ProtocolError("unsupported snapshot version");
    if (version >= 2) {
      // Integrity gate BEFORE any entry parsing: refuse a corrupt file
      // whole rather than half-loading it into a live registry.
      if (raw.size() < 5 + 4)
        throw ProtocolError("truncated snapshot (missing CRC)");
      size_t body = raw.size() - 4;
      uint32_t want = uint32_t(raw[body]) | uint32_t(raw[body + 1]) << 8 |
                      uint32_t(raw[body + 2]) << 16 |
                      uint32_t(raw[body + 3]) << 24;
      uint32_t got = crc32_update(0, raw.data(), body);
      if (got != want)
        throw ProtocolError(
            "snapshot CRC mismatch: truncated or corrupt — refusing to "
            "restore");
      raw.resize(body);
    }
    int64_t srank = int64_t(get_le(8));
    if (srank != cfg_.rank)
      throw std::runtime_error("snapshot rank mismatch");
    registry_.restore_counter(get_le(8));
    uint64_t n = get_le(4);
    for (uint64_t i = 0; i < n; ++i) {
      RegEntry e;
      e.alloc_id = get_le(8);
      e.kind = Kind(uint8_t(get_le(1)));
      e.device_index = uint32_t(get_le(4));
      uint64_t offset = get_le(8);
      e.nbytes = get_le(8);
      e.origin_rank = int64_t(get_le(8));
      e.origin_pid = int64_t(get_le(8));
      uint64_t dlen = get_le(8);
      if (kind_is_host(e.kind)) {
        e.extent = host_arena_.reserve(offset, e.nbytes);
        if (dlen) {
          if (off + dlen > raw.size())
            throw ProtocolError("truncated snapshot data");
          if (dlen > e.nbytes || offset + dlen > host_store_.size())
            throw ProtocolError("snapshot data exceeds its extent");
          std::memcpy(host_store_.data() + offset, raw.data() + off, dlen);
        }
      } else {
        if (e.device_index >= device_books_.size())
          throw ProtocolError("snapshot device_index out of range for this "
                              "daemon's --ndevices");
        e.extent = device_books_[e.device_index]->reserve(offset, e.nbytes);
      }
      off += dlen;
      e.lease_expiry = registry_.new_deadline();
      registry_.insert(e);
      // Resync the master's accounting.
      Message note{MsgType::NOTE_ALLOC,
                   {{"kind", Value::U(uint64_t(e.kind))},
                    {"rank", Value::I(cfg_.rank)},
                    {"device_index", Value::U(e.device_index)},
                    {"nbytes", Value::U(e.nbytes)}},
                   {}};
      if (cfg_.rank == 0) {
        on_note_alloc(note);
      } else {
        try {
          NodeEntry r0 = entry(0);
          peers_.request(r0.caddr(), r0.port, note);
        } catch (const ProtocolError&) {
        }
      }
    }
    std::printf("oncillamemd rank=%lld restored %llu allocations\n",
                (long long)cfg_.rank, (unsigned long long)n);
  }

  // DCN data plane: one-sided put/get into the daemon-owned host arena (the
  // registered-buffer analogue, alloc.c:171-176). Device-kind extents hold
  // their bytes in the SPMD controller's plane arena, so those ops are
  // relayed to the registered plane endpoint (runtime/daemon.py twin).
  Message on_data_put(const Message& m) {
    RegEntry e = registry_.lookup(m.u("alloc_id"));
    uint64_t off = m.u("offset"), n = m.u("nbytes");
    if (!m.data_landed && m.data.size() != n)
      throw ProtocolError("DATA_PUT length mismatch");
    if (off + n > e.nbytes)
      throw BoundsError("access [" + std::to_string(off) + ", " +
                        std::to_string(off + n) + ") outside extent of " +
                        std::to_string(e.nbytes) + " B");
    if (!kind_is_host(e.kind)) return relay_device_op(m, e);
    // data_landed: the payload was recv'd STRAIGHT into the arena extent
    // by route_put_payload (which enforced the same bounds); this
    // post-recv revalidation is what makes the landing durable — a free
    // racing the recv fails the lookup above and answers BAD_ALLOC_ID.
    if (!m.data_landed)
      std::memcpy(host_store_.data() + e.extent.offset + off, m.data.data(),
                  n);
    // Client-facing ack evidence (daemon.py twin): the native daemon
    // serves single-copy chains only, so chain is always 1 and the
    // auditor's replica-ack invariant is trivially satisfied — but the
    // put timeline itself is what the mixed-cluster audit merges.
    if (jrec())
      journal_.record("put_ack", track_,
                      obs::Fields()
                          .u("alloc_id", e.alloc_id)
                          .u("offset", off)
                          .u("nbytes", n)
                          .u("chain", 1)
                          .str());
    return {MsgType::DATA_PUT_OK, {{"nbytes", Value::U(n)}}, {}};
  }

  Message on_data_get(ServeConn& c, const Message& m) {
    RegEntry e = registry_.lookup(m.u("alloc_id"));
    uint64_t off = m.u("offset"), n = m.u("nbytes");
    if (off + n > e.nbytes)
      throw BoundsError("access [" + std::to_string(off) + ", " +
                        std::to_string(off + n) + ") outside extent of " +
                        std::to_string(e.nbytes) + " B");
    if (!kind_is_host(e.kind)) return relay_device_op(m, e);
    Message r{MsgType::DATA_GET_OK, {{"nbytes", Value::U(n)}}, {}};
    // Snapshot copy into this CONNECTION's pooled buffer: keeps the
    // concurrent-free race window bounded to dispatch (a zero-copy arena
    // view would stream freed-then-reused bytes across a stalled send)
    // while skipping the fresh-allocation cost per chunk.
    r.data = take_bulk_buffer(c.bulk_buf,
                              host_store_.data() + e.extent.offset + off, n);
    return r;
  }

  // -- cross-process device plane (PLANE_SERVE / PLANE_PUT / PLANE_GET) --

  Message on_plane_serve(const Message& m) {
    std::string host = m.u("port") ? m.s("host") : "";  // port 0 = clear
    int port = int(m.u("port"));
    {
      std::lock_guard<std::mutex> g(plane_mu_);
      if (host == plane_host_ && port == plane_port_ && m.u("relay") != 0) {
        // Gossiped copy of what we already hold: nothing to do. (An
        // UNCHANGED client re-registration still re-arms the gossip
        // below — a restarted peer daemon re-learns the endpoint.)
        return {MsgType::PLANE_SERVE_OK, {{"port", Value::U(m.u("port"))}},
                {}};
      }
      plane_host_ = host;
      plane_port_ = port;
    }
    if (m.u("relay") == 0) {
      // Fresh (de)registration from a local client: the master matters
      // most (it is everyone's fallback hop), so push there inline — one
      // dial. The rest of the peers are retried from the reaper loop; a
      // synchronous broadcast here would stall the registering client
      // for the connect timeout per unreachable peer.
      size_t n;
      {
        std::lock_guard<std::mutex> ge(entries_mu_);
        n = entries_.size();
      }
      {
        std::lock_guard<std::mutex> g(plane_mu_);
        plane_unsynced_.clear();
        for (size_t r = 0; r < n; ++r)
          if (int64_t(r) != cfg_.rank) plane_unsynced_.insert(int64_t(r));
      }
      if (cfg_.rank != 0) sync_plane_endpoint(/*only_rank=*/0);
    }
    return {MsgType::PLANE_SERVE_OK, {{"port", Value::U(m.u("port"))}}, {}};
  }

  // only_rank == -1: push to every pending peer (reaper); otherwise only
  // to that rank.
  void sync_plane_endpoint(int64_t only_rank = -1) {
    std::string host;
    int port = 0;
    std::vector<int64_t> pending;
    {
      std::lock_guard<std::mutex> g(plane_mu_);
      host = plane_host_;
      port = plane_port_;
      pending.assign(plane_unsynced_.begin(), plane_unsynced_.end());
    }
    for (int64_t r : pending) {
      if (only_rank >= 0 && r != only_rank) continue;
      try {
        NodeEntry e = entry(r);
        peers_.request(e.caddr(), e.port,
                       Message{MsgType::PLANE_SERVE,
                               {{"host", Value::S(host)},
                                {"port", Value::U(uint64_t(port))},
                                {"relay", Value::U(1)}},
                               {}});
        std::lock_guard<std::mutex> g(plane_mu_);
        plane_unsynced_.erase(r);
      } catch (const std::exception&) {
        // retried on the next reaper tick
      }
    }
  }

  Message relay_device_op(const Message& m, const RegEntry& e) {
    if (m.type == MsgType::DATA_PUT) device_writes_relayed_ = true;
    Message relay{
        m.type == MsgType::DATA_PUT ? MsgType::PLANE_PUT : MsgType::PLANE_GET,
        {{"alloc_id", Value::U(e.alloc_id)},
         {"rank", Value::I(cfg_.rank)},
         {"device_index", Value::U(e.device_index)},
         {"ext_offset", Value::U(e.extent.offset)},
         {"ext_nbytes", Value::U(e.nbytes)},
         {"offset", Value::U(m.u("offset"))},
         {"nbytes", Value::U(m.u("nbytes"))}},
        m.data};
    return forward_to_plane(relay);
  }

  Message forward_to_plane(const Message& relay) {
    std::string host;
    int port = 0;
    {
      std::lock_guard<std::mutex> g(plane_mu_);
      host = plane_host_;
      port = plane_port_;
    }
    if (!host.empty()) {
      try {
        return peers_.request(host, port, relay);
      } catch (const std::exception&) {
        // Endpoint unreachable (controller gone without deregistering):
        // drop it — live controllers re-register periodically — and fall
        // through to the master hop / typed error.
        std::lock_guard<std::mutex> g(plane_mu_);
        if (plane_host_ == host && plane_port_ == port) {
          plane_host_.clear();
          plane_port_ = 0;
        }
      }
    }
    if (cfg_.rank != 0) {  // master hop: it learns endpoints first
      NodeEntry r0 = entry(0);
      return peers_.request(r0.caddr(), r0.port, relay);
    }
    throw BadHandleError(
        "device-kind data needs a registered plane: construct the "
        "controller's ControlPlaneClient with ici_plane=");
  }

  Message on_heartbeat(const Message& m) {
    registry_.renew(m.i("pid"), m.i("rank"));
    lease_renewals_.fetch_add(1, std::memory_order_relaxed);
    if (jrec())
      journal_.record("lease_renew", track_,
                      obs::Fields()
                          .i("app_pid", m.i("pid"))
                          .i("app_rank", m.i("rank"))
                          .b("relayed", m.i("rank") != cfg_.rank)
                          .str());
    // Relay local-app heartbeats only to the ranks the app reports as
    // owners of its allocations — O(owners) per beat, not an O(nnodes)
    // broadcast. Relayed copies have origin rank != receiver rank, so no
    // forwarding loop.
    if (m.i("rank") == cfg_.rank) {
      for (int64_t r : parse_owners(m.s("owners"))) {
        if (r == cfg_.rank || r < 0 || size_t(r) >= entries_.size()) continue;
        try {
          NodeEntry e = entry(r);
          peers_.request(e.caddr(), e.port, m);
        } catch (const ProtocolError&) {
        }
      }
    }
    return {MsgType::HEARTBEAT_OK,
            {{"lease_s", Value::D(registry_.lease_s())}},
            {}};
  }

  // Immediate reclamation on app disconnect (main.c:46-47,58-103): free
  // local allocations now, and fan RECLAIM_APP out to the owner ranks the
  // app reported. A crashed app never disconnects — the lease reaper is the
  // backstop.
  void on_disconnect(const Message& m) {
    int64_t pid = m.i("pid");
    // Terminal event for the app's lease-renewal chain: the auditor
    // requires every renewing app to end in disconnect/free/reclaim.
    if (jrec())
      journal_.record("app_disconnect", track_,
                      obs::Fields().i("pid", pid).str());
    reclaim_app_local(pid, cfg_.rank);
    for (int64_t r : parse_owners(m.s("owners"))) {
      if (r == cfg_.rank || r < 0 || size_t(r) >= entries_.size()) continue;
      try {
        NodeEntry e = entry(r);
        peers_.request(e.caddr(), e.port,
                       {MsgType::RECLAIM_APP,
                        {{"pid", Value::I(pid)}, {"rank", Value::I(cfg_.rank)}},
                        {}});
      } catch (const ProtocolError&) {
      }
    }
  }

  uint64_t reclaim_app_local(int64_t pid, int64_t origin_rank) {
    uint64_t n = 0;
    for (uint64_t id : registry_.ids_for_app(pid, origin_rank)) {
      try {
        do_free_local(id);
        ++n;
      } catch (const BadHandleError&) {  // raced with an explicit free
      }
    }
    return n;
  }

  static std::vector<int64_t> parse_owners(const std::string& s) {
    std::vector<int64_t> out;
    size_t pos = 0;
    while (pos <= s.size()) {
      size_t comma = s.find(',', pos);
      std::string part = s.substr(
          pos, comma == std::string::npos ? std::string::npos : comma - pos);
      if (!part.empty()) {
        try {
          out.push_back(std::stoll(part));
        } catch (const std::exception&) {
        }
      }
      if (comma == std::string::npos) break;
      pos = comma + 1;
    }
    return out;
  }

  Message on_status() {
    uint64_t dev_live = 0;
    for (auto& b : device_books_) dev_live += b->bytes_live();
    return {MsgType::STATUS_OK,
            {{"rank", Value::I(cfg_.rank)},
             {"nnodes", Value::I(cfg_.rank == 0 ? placement_.nnodes()
                                                : int64_t(entries_.size()))},
             {"live_allocs", Value::U(registry_.live_count())},
             {"host_bytes_live", Value::U(host_arena_.bytes_live())},
             {"device_bytes_live", Value::U(dev_live)}},
            {}};
  }

  // -- in-band observability (STATUS_PROM / STATUS_EVENTS) ---------------

  // Prometheus text exposition rendered natively (obs/prom.py's format,
  // validated by the same Python format checker): the metrics subset a
  // native daemon owns — cluster view, op spans, arena occupancy and
  // churn, lease health. Families the native daemon has no machinery
  // for (replication, QoS, fabric, elastic) are simply absent, exactly
  // like a Python daemon with those subsystems idle.
  Message on_status_prom() {
    using obs::PromDoc;
    PromDoc doc;
    std::string rank = std::to_string(cfg_.rank);
    doc.sample("ocm_nnodes", "gauge",
               "Cluster size as this daemon sees it.",
               double(cfg_.rank == 0 ? placement_.nnodes()
                                     : int64_t(entries_.size())),
               {{"rank", rank}});
    doc.sample("ocm_live_allocs", "gauge",
               "Live allocations registered on this daemon.",
               double(registry_.live_count()), {{"rank", rank}});
    for (const auto& kv : opstats_.snapshot()) {
      PromDoc::Labels lab{{"rank", rank}, {"op", kv.first}};
      doc.sample("ocm_op_total", "counter",
                 "Completed Tracer spans per op.", double(kv.second.count),
                 lab);
      doc.sample("ocm_op_bytes_total", "counter",
                 "Bytes moved by completed spans per op.",
                 double(kv.second.total_bytes), lab);
      doc.sample("ocm_op_p50_seconds", "gauge",
                 "p50 span latency over the sample ring.",
                 kv.second.p50_s, lab);
      doc.sample("ocm_op_p99_seconds", "gauge",
                 "p99 span latency over the sample ring.",
                 kv.second.p99_s, lab);
      doc.sample("ocm_op_gigabits_per_second", "gauge",
                 "Lifetime mean throughput per op (gigabits/s).",
                 kv.second.total_s > 0
                     ? double(kv.second.total_bytes) * 8 /
                           kv.second.total_s / 1e9
                     : 0.0,
                 lab);
    }
    auto arena_rows = [&](const std::string& name, uint64_t live,
                          uint64_t cap, uint64_t allocs, uint64_t frees) {
      doc.sample("ocm_arena_live_bytes", "gauge",
                 "Bytes currently reserved in an arena.", double(live),
                 {{"rank", rank}, {"arena", name}});
      doc.sample("ocm_arena_capacity_bytes", "gauge",
                 "Arena capacity in bytes.", double(cap),
                 {{"rank", rank}, {"arena", name}});
      doc.sample("ocm_arena_ops_total", "counter",
                 "Lifetime arena operations (allocation churn).",
                 double(allocs),
                 {{"rank", rank}, {"arena", name}, {"op", "alloc"}});
      doc.sample("ocm_arena_ops_total", "counter",
                 "Lifetime arena operations (allocation churn).",
                 double(frees),
                 {{"rank", rank}, {"arena", name}, {"op", "free"}});
    };
    arena_rows("host", host_arena_.bytes_live(), cfg_.host_arena_bytes,
               host_arena_.alloc_count(), host_arena_.release_count());
    for (size_t i = 0; i < device_books_.size(); ++i)
      arena_rows("device" + std::to_string(i), device_books_[i]->bytes_live(),
                 cfg_.device_arena_bytes, device_books_[i]->alloc_count(),
                 device_books_[i]->release_count());
    doc.sample("ocm_lease_renewals_total", "counter",
               "Heartbeat-driven lease renewals processed.",
               double(lease_renewals_.load()), {{"rank", rank}});
    doc.sample("ocm_lease_reclaims_total", "counter",
               "Allocations the lease reaper took back.",
               double(lease_reclaims_.load()), {{"rank", rank}});
    doc.sample("ocm_leases_expired", "gauge",
               "Live allocations currently past their lease.",
               double(registry_.expired().size()), {{"rank", rank}});
    std::string text = doc.text();
    Message r{MsgType::STATUS_PROM_OK, {{"rank", Value::I(cfg_.rank)}}, {}};
    r.data.assign(text.begin(), text.end());
    return r;
  }

  // The journal ring as JSONL — exactly journal.py dump_jsonl's record
  // shape, so the obs CLI's --trace cluster merge and the Perfetto
  // exporter consume a native rank with zero changes.
  Message on_status_events() {
    std::string jsonl = journal_.dump_jsonl();
    uint64_t count = 0;
    for (char ch : jsonl)
      if (ch == '\n') ++count;
    Message r{MsgType::STATUS_EVENTS_OK,
              {{"rank", Value::I(cfg_.rank)}, {"count", Value::U(count)}},
              {}};
    r.data.assign(jsonl.begin(), jsonl.end());
    return r;
  }

  NodeEntry entry(int64_t rank) {
    std::lock_guard<std::mutex> g(entries_mu_);
    return entries_.at(size_t(rank));
  }

  Config cfg_;
  std::vector<NodeEntry> entries_;
  std::mutex entries_mu_;
  // Device-plane endpoint registered via PLANE_SERVE (empty host = none);
  // plane_unsynced_ = peer ranks that have not confirmed the endpoint yet
  // (pushed again from the reaper loop).
  std::mutex plane_mu_;
  std::string plane_host_;
  int plane_port_ = 0;
  std::set<int64_t> plane_unsynced_;
  std::atomic<bool> device_writes_relayed_{false};
  ArenaAllocator host_arena_;
  std::vector<uint8_t> host_store_;  // the DCN arm's actual bytes
  std::vector<std::unique_ptr<ArenaAllocator>> device_books_;
  Registry registry_;
  Placement placement_;
  PeerPool peers_;
  // Observability (obs.hh): journal ring + flight recorder + op spans.
  // obs_enabled_ is the OCM_NATIVE_OBS master switch (default on);
  // caps_mask_ is what CONNECT_CONFIRM echoes.
  std::string track_;
  bool obs_enabled_ = true;
  uint16_t caps_mask_ = kCapsImplemented;
  obs::Journal journal_;
  obs::OpStatsBook opstats_;
  std::atomic<uint64_t> lease_renewals_{0};
  std::atomic<uint64_t> lease_reclaims_{0};
  std::atomic<bool> signalled_{false};
  std::atomic<bool> running_{false};
  std::thread reaper_thread_;
  // Per-message control threads (blocking semantics preserved), reaped
  // from the reaper loop via finished_.
  std::vector<std::thread> serve_threads_;
  std::mutex reap_mu_;
  std::vector<std::thread::id> finished_;
  // DATA-plane worker pool.
  std::vector<std::thread> pool_threads_;
  std::mutex queue_mu_;
  std::condition_variable queue_cv_;
  std::deque<Work> queue_;
  bool queue_stop_ = false;
  bool started_ok_ = false;
  std::mutex conns_mu_;
  std::map<int, std::shared_ptr<ServeConn>> conns_;
  int listen_fd_ = -1;
  int epoll_fd_ = -1;
  int wake_fd_ = -1;
};

Daemon* g_daemon = nullptr;

void on_signal(int) {
  if (g_daemon) g_daemon->request_stop();
}

}  // namespace
}  // namespace ocm

int main(int argc, char** argv) {
  ocm::Config cfg;
  if (const char* bh = getenv("OCM_BIND_HOST")) cfg.bind_host = bh;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      if (i + 1 >= argc) throw std::runtime_error("missing value for " + a);
      return argv[++i];
    };
    if (a == "--nodefile") cfg.nodefile = next();
    else if (a == "--rank") cfg.rank = std::stoll(next());
    else if (a == "--policy") cfg.capacity_policy = next() == "capacity";
    else if (a == "--ndevices") cfg.ndevices = uint32_t(std::stoul(next()));
    else if (a == "--host-arena-bytes") cfg.host_arena_bytes = std::stoull(next());
    else if (a == "--device-arena-bytes") cfg.device_arena_bytes = std::stoull(next());
    else if (a == "--alignment") cfg.alignment = std::stoull(next());
    else if (a == "--lease-s") cfg.lease_s = std::stod(next());
    else if (a == "--heartbeat-s") cfg.heartbeat_s = std::stod(next());
    else if (a == "--snapshot") cfg.snapshot_path = next();
    else if (a == "--bind-host") cfg.bind_host = next();
    else {
      std::fprintf(stderr, "unknown flag %s\n", a.c_str());
      return 2;
    }
  }
  if (cfg.nodefile.empty() || cfg.rank < 0) {
    std::fprintf(stderr,
                 "usage: oncillamemd --nodefile FILE --rank N [--policy "
                 "capacity|neighbor] [--ndevices N] [--host-arena-bytes N] "
                 "[--device-arena-bytes N] [--alignment N] [--lease-s S] "
                 "[--heartbeat-s S]\n");
    return 2;
  }
  try {
    auto entries = ocm::parse_nodefile(cfg.nodefile);
    ocm::Daemon d(cfg, entries);
    ocm::g_daemon = &d;
    signal(SIGINT, ocm::on_signal);
    signal(SIGTERM, ocm::on_signal);
    d.run();
  } catch (const std::exception& e) {
    std::fprintf(stderr, "oncillamemd: fatal: %s\n", e.what());
    return 1;
  }
  return 0;
}
