"""Error types for oncilla-tpu.

The reference signals errors with -1 returns and ``BUG()``/``ABORT()`` crash
macros (/root/reference/inc/debug.h:32-48). Here errors are typed exceptions.
"""

from __future__ import annotations


class OcmError(Exception):
    """Base class for all oncilla-tpu errors."""


class OcmOutOfMemory(OcmError):
    """Arena cannot satisfy the requested allocation."""


class OcmBoundsError(OcmError):
    """A put/get would run outside the allocation, analogue of the bounds
    checks in post_send (/root/reference/src/rdma.c:55-59)."""


class OcmInvalidHandle(OcmError):
    """Handle is freed, unknown, or of the wrong kind for the operation."""


class OcmProtocolError(OcmError):
    """Malformed or unexpected control-plane message (transport-level: the
    connection can no longer be trusted)."""


class OcmRemoteError(OcmProtocolError):
    """A peer replied with a well-formed ERROR message. The connection
    remains in sync and reusable; ``code`` is the wire ErrCode value."""

    def __init__(self, code: int, detail: str):
        super().__init__(detail)
        self.code = code
        self.detail = detail


class OcmConnectError(OcmError):
    """Could not reach the local daemon or a peer daemon."""


class OcmReplicaUnavailable(OcmError):
    """A replicated write could not reach a replica that is not (yet)
    declared DEAD — the primary refuses to ack a put it cannot make
    durable on the chain (wire: ErrCode.REPLICA_UNAVAILABLE, retryable)."""


class OcmNotPrimary(OcmError):
    """A replica holder refused a client data op because it still
    believes its primary alive (wire: ErrCode.NOT_PRIMARY, retryable —
    the failover window closes when the death verdict lands)."""


class OcmPlacementError(OcmError):
    """The placement policy could not site the allocation."""


class OcmQuotaExceeded(OcmError):
    """The app's byte or handle quota cannot admit this allocation
    (wire: ErrCode.QUOTA_EXCEEDED, not retryable until the app frees)."""


class OcmAdmissionDenied(OcmError):
    """Admission control refused the app outright — e.g. the daemon's
    concurrent-app cap is reached (wire: ErrCode.ADMISSION_DENIED)."""


class OcmMoved(OcmError):
    """The allocation was live-migrated off this rank (elastic/): the
    source holds a forwarding tombstone naming the new owner (wire:
    ErrCode.MOVED, retryable; ``rank`` rides as an i64 data tail on the
    ERROR frame and clients repoint their handle at it)."""

    def __init__(self, detail: str, rank: int):
        super().__init__(detail)
        self.rank = int(rank)


class OcmDeadlineExceeded(OcmError):
    """The op's time budget ran out (resilience/timebudget.py) — locally
    (a retry ladder clamped to zero remaining) or remotely (a daemon
    refused already-expired work; wire: ErrCode.DEADLINE_EXCEEDED). Not
    retryable: the budget is the caller's own contract, and surfacing it
    typed is the whole point — a decode step that misses its token
    budget sheds instead of hanging the batch."""


class OcmBreakerOpen(OcmConnectError):
    """A per-peer circuit breaker is OPEN (resilience/timebudget.py):
    consecutive transport/deadline failures flipped the peer and this
    attempt failed FAST instead of eating the op's budget. A subclass of
    OcmConnectError on purpose — failover ladders treat it exactly like
    an unreachable peer and walk to the next candidate; half-open probes
    re-admit the peer once it answers again."""


class OcmBusy(OcmError):
    """Back-pressure: the arena(s) crossed the high watermark and the
    daemon asks the client to retry later (wire: ErrCode.BUSY, retryable;
    ``retry_after_ms`` is the server-suggested backoff, carried as a u32
    data tail on the ERROR frame)."""

    def __init__(self, detail: str, retry_after_ms: int = 0):
        super().__init__(detail)
        self.retry_after_ms = int(retry_after_ms)
