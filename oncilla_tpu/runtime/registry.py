"""Allocation registry with monotonic ids and leases.

Analogue of the reference's ``rem_alloc_id`` counter + per-node allocation
lists (/root/reference/src/mem.c:45,345-348; alloc.c:41-43,242-255), with two
fixes SURVEY.md mandates: the rank-0 bookkeeping actually removes entries on
free (the reference's ``root_allocs`` list grows forever, alloc.c:134-137,
and its free path is a stub, mem.c:221-229), and entries carry leases so a
dead app's allocations are reclaimed (the unresolved TODO, main.c:6-7).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from oncilla_tpu.analysis.lockwatch import make_lock
from oncilla_tpu.core.arena import Extent
from oncilla_tpu.core.errors import OcmInvalidHandle
from oncilla_tpu.core.kinds import OcmKind


@dataclass
class RegEntry:
    alloc_id: int
    kind: OcmKind
    rank: int            # owner rank
    device_index: int
    extent: Extent
    nbytes: int          # user-requested size
    origin_rank: int
    origin_pid: int
    lease_expiry: float  # absolute monotonic deadline; renewed by heartbeat
    # Replication (resilience/): the ordered owner chain of a k-way
    # replicated allocation — chain[0] is the primary, the rest hold
    # replicas. Every holder records the SAME chain, so when rank 0
    # declares a member DEAD each survivor computes the identical
    # promotion locally (first alive member becomes primary). () = the
    # unreplicated common case. ``epoch`` stamps the cluster epoch of the
    # last chain rewrite (failover fencing evidence).
    chain: tuple[int, ...] = ()
    epoch: int = 0
    # QoS priority class (qos/): 0 low, 1 normal, 2 high. Carried from
    # the app's CONNECT declaration via the FLAG_QOS_TAIL alloc tails;
    # the reaper's pressure eviction orders victims by it and never
    # touches an ACTIVE entry above class 0. Snapshot-restored entries
    # come back at the default (the snapshot format predates priorities).
    priority: int = 1
    # Live migration quarantine (elastic/): a MIGRATE_BEGIN-provisioned
    # copy is ``migrating`` until the flip's chain rewrite lands — it
    # refuses client ops (only FLAG_FANOUT stream/mirror writes land)
    # and is DROPPED, not promoted, if ``migrate_src`` dies mid-stream:
    # a half-streamed copy must never serve or fork a chain.
    migrating: bool = False
    migrate_src: int = -1
    # FROZEN tier (persist/): True while the payload lives in the
    # daemon's FrozenStore instead of the host arena. ``extent`` is a
    # zero placeholder meanwhile (the arena bytes were freed at
    # demotion); the first client data op thaws the entry back into the
    # arena. A frozen entry is never an eviction candidate — it holds
    # no arena bytes, and destroying it would silently lose durable
    # payload (the audit's eviction-priority invariant pins this).
    frozen: bool = False

    def is_primary(self, self_rank: int) -> bool:
        """Primary = unreplicated owner, or first member of the chain."""
        return not self.chain or self.chain[0] == self_rank

    def replica_ranks(self, self_rank: int) -> tuple[int, ...]:
        """Ranks this holder must fan writes out to (primary only)."""
        if self.chain and self.chain[0] == self_rank:
            return self.chain[1:]
        return ()


class AllocRegistry:
    """Owner-side registry of live allocations. Ids are even and globally
    unique per daemon: ``id = rank * 2^32 + counter*2`` (apps use odd local
    ids, so the spaces never collide)."""

    def __init__(self, rank: int, lease_s: float = 30.0,
                 app_stale_leases: float = 10.0):
        self._rank = rank
        self._lease_s = lease_s
        # Heartbeat-silence threshold (in lease periods) before an app's
        # row is pruned from the per-app view (config.app_stale_leases;
        # previously a hardcoded 10).
        self._app_stale_leases = app_stale_leases
        self._counter = 0
        self._entries: dict[int, RegEntry] = {}
        self._lock = make_lock("registry._lock")
        # Lease/heartbeat health counters — what Ocm.status() surfaces so
        # the cluster CLI's "lease pressure" column has real data: how
        # often leases were renewed, how many the reaper took back, and
        # when each app was last heard from.
        self._renewals = 0
        self._reclaims = 0
        self._last_beat: dict[tuple[int, int], float] = {}  # (pid, rank)

    def next_id(self) -> int:
        with self._lock:
            self._counter += 1
            return (self._rank << 32) | (self._counter << 1)

    @property
    def counter(self) -> int:
        with self._lock:
            return self._counter

    def restore_counter(self, value: int) -> None:
        with self._lock:
            self._counter = max(self._counter, value)

    def insert(self, entry: RegEntry) -> None:
        with self._lock:
            self._entries[entry.alloc_id] = entry

    def lookup(self, alloc_id: int) -> RegEntry:
        with self._lock:
            e = self._entries.get(alloc_id)
        if e is None:
            raise OcmInvalidHandle(f"unknown alloc_id {alloc_id}")
        return e

    def remove(self, alloc_id: int) -> RegEntry:
        with self._lock:
            e = self._entries.pop(alloc_id, None)
        if e is None:
            raise OcmInvalidHandle(f"unknown alloc_id {alloc_id}")
        return e

    def renew_leases(self, origin_pid: int, origin_rank: int) -> None:
        now = time.monotonic()
        deadline = now + self._lease_s
        with self._lock:
            self._renewals += 1
            self._last_beat[(origin_pid, origin_rank)] = now
            for e in self._entries.values():
                if e.origin_pid == origin_pid and e.origin_rank == origin_rank:
                    e.lease_expiry = deadline

    def note_reclaim(self, n: int = 1) -> None:
        """Count allocations the lease reaper took back."""
        with self._lock:
            self._reclaims += n

    def lease_stats(self, now: float | None = None) -> dict:
        """Lease/heartbeat health: renewal + reaper-reclaim totals, how
        many live entries are past their lease right now, and seconds
        since each app's last heartbeat. Apps silent for
        ``app_stale_leases`` lease periods are pruned from the per-app
        view (the dict must not grow with every app that ever
        attached)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            stale = [
                k for k, t in self._last_beat.items()
                if now - t > self._app_stale_leases * self._lease_s
            ]
            for k in stale:
                del self._last_beat[k]
            return {
                "renewals": self._renewals,
                "reclaims": self._reclaims,
                "expired": sum(
                    1 for e in self._entries.values()
                    if e.lease_expiry < now
                ),
                "lease_s": self._lease_s,
                "apps": {
                    f"{pid}@r{rank}": round(now - t, 3)
                    for (pid, rank), t in self._last_beat.items()
                },
            }

    def set_chain(self, alloc_id: int, chain: tuple[int, ...],
                  epoch: int) -> None:
        """Record (or rewrite) an allocation's replica chain. A chain
        rewrite clears migration quarantine: the flip's DO_REPLICA push
        is the only rewrite a quarantined copy ever sees while its
        source lives (a dead source goes through abort_migrations
        BEFORE any reconcile touches chains)."""
        with self._lock:
            e = self._entries.get(alloc_id)
            if e is None:
                raise OcmInvalidHandle(f"unknown alloc_id {alloc_id}")
            e.chain = tuple(chain)
            e.epoch = epoch
            e.migrating = False
            e.migrate_src = -1

    def mark_migrating(self, alloc_id: int, chain: tuple[int, ...],
                       epoch: int, src: int) -> None:
        """Re-quarantine an existing entry as an in-flight migration
        copy (a retried MIGRATE_BEGIN after a lost reply): chain, epoch
        and quarantine state set under one lock."""
        with self._lock:
            e = self._entries.get(alloc_id)
            if e is None:
                raise OcmInvalidHandle(f"unknown alloc_id {alloc_id}")
            e.chain = tuple(chain)
            e.epoch = epoch
            e.migrating = True
            e.migrate_src = src

    def abort_migrations(self, dead: set[int]) -> list[RegEntry]:
        """Drop quarantined migration copies whose source rank died
        mid-stream (elastic/): a half-streamed copy must never be
        promoted or repaired into a chain. Returns the removed entries
        so the daemon can free their arena extents and journal the
        aborts. MUST run before reconcile_dead for the same dead set."""
        with self._lock:
            doomed = [
                e for e in self._entries.values()
                if e.migrating and e.migrate_src in dead
            ]
            for e in doomed:
                del self._entries[e.alloc_id]
        return doomed

    def reconcile_dead(
        self, dead: set[int], self_rank: int, epoch: int
    ) -> tuple[list[RegEntry], list[dict]]:
        """Drop ``dead`` ranks from every replica chain (resilience/
        failover.py). Returns (newly promoted entries, re-replication work
        list): an entry whose chain's first ALIVE member is ``self_rank``
        is promoted here — registry ownership rewritten under ``epoch`` —
        and every entry this rank is primary for that now holds fewer
        copies than it was built with is reported for repair. Each holder
        of a chain runs the same pure computation, so no coordination
        beyond the dead-set is needed."""
        promoted: list[RegEntry] = []
        repair: list[dict] = []
        with self._lock:
            for e in self._entries.values():
                if not e.chain or not (set(e.chain) & dead):
                    continue
                want = len(e.chain)
                alive = tuple(r for r in e.chain if r not in dead)
                if not alive:
                    continue  # unreachable: this holder is alive
                was_primary = e.chain[0] == self_rank
                e.chain = alive
                e.epoch = epoch
                if alive[0] != self_rank:
                    continue
                if e.migrating:
                    # A quarantined migration copy is never promoted —
                    # abort_migrations (run first) drops it when its
                    # source died; this guard covers any other ordering.
                    continue
                if not was_primary:
                    promoted.append(e)
                if len(alive) < want:
                    repair.append({
                        "alloc_id": e.alloc_id,
                        "kind": e.kind.value,
                        "nbytes": e.nbytes,
                        "chain": list(alive),
                        "want": want,
                        "origin_rank": e.origin_rank,
                        "origin_pid": e.origin_pid,
                    })
        return promoted, repair

    def for_app(self, origin_pid: int, origin_rank: int) -> list[RegEntry]:
        """Every allocation originated by an app — feeds the disconnect-time
        reclamation the reference left as a TODO
        (/root/reference/src/main.c:6-7,58-103)."""
        with self._lock:
            return [
                e for e in self._entries.values()
                if e.origin_pid == origin_pid and e.origin_rank == origin_rank
            ]

    def expired(self, now: float | None = None) -> list[RegEntry]:
        now = time.monotonic() if now is None else now
        with self._lock:
            return [e for e in self._entries.values() if e.lease_expiry < now]

    def eviction_candidates(
        self, self_rank: int, now: float | None = None
    ) -> list[RegEntry]:
        """Victim order for the reaper's pressure eviction (qos/):
        host-kind entries this rank is PRIMARY for (evicting a replica
        copy out from under its chain would silently degrade k), sorted
        expired-first, then priority ascending, then oldest lease. The
        caller enforces the class invariant — an ACTIVE entry above
        priority 0 is never evicted — this just supplies the queue."""
        now = time.monotonic() if now is None else now
        with self._lock:
            cands = [
                e for e in self._entries.values()
                if e.kind in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST)
                and e.is_primary(self_rank)
                and not e.frozen
            ]
        cands.sort(
            key=lambda e: (e.lease_expiry >= now, e.priority, e.lease_expiry)
        )
        return cands

    def new_lease_deadline(self) -> float:
        return time.monotonic() + self._lease_s

    @property
    def lease_s(self) -> float:
        return self._lease_s

    def live_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def live_bytes(self, kind_filter=None) -> int:
        with self._lock:
            return sum(
                e.extent.nbytes
                for e in self._entries.values()
                if kind_filter is None or e.kind == kind_filter
            )

    def snapshot(self) -> list[RegEntry]:
        with self._lock:
            return list(self._entries.values())
