"""ICI data-plane tests on the 8-device virtual mesh: REMOTE_DEVICE
allocations through the control plane with data riding the device fabric —
the end-to-end slice of SURVEY.md §7 step 3 (ocm_test tests 1-3 for the
device arm), plus the SpmdArena in-mesh fabric."""

import jax
import numpy as np
import pytest

import oncilla_tpu as ocm
from oncilla_tpu import OcmKind
from oncilla_tpu.ops.ici import IciDataPlane, SpmdIciPlane
from oncilla_tpu.parallel import spmd_arena as sa
from oncilla_tpu.parallel.mesh import node_mesh
from oncilla_tpu.runtime.cluster import local_cluster
from oncilla_tpu.utils.config import OcmConfig


def cfg(**kw):
    d = dict(
        host_arena_bytes=4 << 20,
        device_arena_bytes=2 << 20,
        heartbeat_s=0.5,
    )
    d.update(kw)
    return OcmConfig(**d)


@pytest.fixture
def cluster2x4():
    # 2 "hosts" x 4 chips over the 8 virtual devices.
    c = OcmConfig(host_arena_bytes=4 << 20, device_arena_bytes=2 << 20)
    with local_cluster(2, config=c, ndevices=4) as cl:
        plane = IciDataPlane(config=c, devices=jax.devices(), devices_per_rank=4)
        yield cl, plane


def test_remote_device_put_get_roundtrip(cluster2x4, rng):
    cl, plane = cluster2x4
    ctx = cl.context(0, ici_plane=plane)
    h = ctx.alloc(256 << 10, OcmKind.REMOTE_DEVICE)
    assert h.rank == 1  # placed off-origin
    data = rng.integers(0, 256, 256 << 10, dtype=np.uint8)
    ctx.put(h, data)
    out = np.asarray(ctx.get(h))
    np.testing.assert_array_equal(out, data)
    # Bytes physically live in the owner chip's arena at the handle's extent.
    from oncilla_tpu.parallel.mesh import global_index

    g = global_index(h.rank, h.device_index, 4)
    row = np.asarray(plane.arenas[g].read(h.extent, 256 << 10))
    np.testing.assert_array_equal(row, data)
    ctx.free(h)


def test_remote_device_typed(cluster2x4):
    import jax.numpy as jnp

    cl, plane = cluster2x4
    client = cl.client(0, ici_plane=plane)
    h = client.alloc(4 * 1024, OcmKind.REMOTE_DEVICE)
    x = jnp.arange(1024, dtype=jnp.float32)
    client.put(h, x, 0)
    y = plane.get_as(h, (1024,), jnp.float32)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x))
    client.free(h)


def test_ici_copy_chip_to_chip(cluster2x4, rng):
    cl, plane = cluster2x4
    ctx = cl.context(0, ici_plane=plane)
    ctx1 = cl.context(1, ici_plane=plane)
    h0 = ctx1.alloc(128 << 10, OcmKind.REMOTE_DEVICE)  # on rank 0 devices
    h1 = ctx.alloc(128 << 10, OcmKind.REMOTE_DEVICE)   # on rank 1 devices
    assert (h0.rank, h1.rank) == (0, 1)
    data = rng.integers(0, 256, 128 << 10, dtype=np.uint8)
    plane.put(h0, data)
    plane.copy(h1, h0, 128 << 10)
    np.testing.assert_array_equal(np.asarray(plane.get(h1, 128 << 10)), data)
    ctx.free(h1)
    ctx1.free(h0)


def test_device_arm_needs_ici_plane(cluster2x4):
    """A plane-less client's device op is relayed by the owner daemon;
    with no plane registered ANYWHERE it fails with a typed error naming
    the fix (when a controller serves one, the same call succeeds —
    tests/test_plane_relay.py)."""
    cl, _ = cluster2x4
    client = cl.client(0)  # no plane
    h = client.alloc(4096, OcmKind.REMOTE_DEVICE)
    with pytest.raises(ocm.OcmError, match="registered plane"):
        client.put(h, np.zeros(16, np.uint8), 0)
    client.free(h)


def test_ici_copy_dispatch_is_async(cluster2x4, rng):
    """The chunk loop's pipelining mechanism is async dispatch (PJRT
    overlaps chunk i+1's read/transfer with chunk i's destination update).
    Enforced here: every chunk goes through an async D2D device_put, and
    the module-level sync entry points (jax.block_until_ready /
    jax.device_get) are unreachable. (Method-level .block_until_ready()
    lives on an unpatchable C type and is covered by code review, not this
    test.)"""
    from unittest import mock

    cl, plane = cluster2x4
    ctx = cl.context(0, ici_plane=plane)
    ctx1 = cl.context(1, ici_plane=plane)
    h0 = ctx1.alloc(96 << 10, OcmKind.REMOTE_DEVICE)
    h1 = ctx.alloc(96 << 10, OcmKind.REMOTE_DEVICE)
    data = rng.integers(0, 256, 96 << 10, dtype=np.uint8)
    plane.put(h0, data)

    # Chunked: 16 KB chunks over 96 KB => 6 chunks through a 2-deep window.
    plane.config.chunk_bytes = 16 << 10
    calls = {"n": 0}
    real_dp = jax.device_put

    def counting_device_put(x, *a, **k):
        calls["n"] += 1
        return real_dp(x, *a, **k)

    def no_sync(*a, **k):
        raise AssertionError("copy loop synchronized on data")

    with mock.patch.object(jax, "device_put", counting_device_put), \
         mock.patch.object(jax, "block_until_ready", no_sync), \
         mock.patch.object(jax, "device_get", no_sync):
        plane.copy(h1, h0, 96 << 10)
    assert calls["n"] >= 6  # every chunk went through an async D2D dispatch

    np.testing.assert_array_equal(np.asarray(plane.get(h1, 96 << 10)), data)
    ctx.free(h1)
    ctx1.free(h0)


# -- SpmdIciPlane: handles wired to the one-sided fabric ------------------


@pytest.fixture
def spmd_cluster():
    # 2 "hosts" x 4 chips; handles resolve onto the mesh-sharded arena.
    # Small rows keep this fixture's many tests fast; MiB-scale extents
    # through the windowed interpret path are covered by
    # test_spmd_plane_mib_scale_pallas_copy.
    c = OcmConfig(host_arena_bytes=4 << 20, device_arena_bytes=64 << 10)
    with local_cluster(2, config=c, ndevices=4) as cl:
        plane = SpmdIciPlane(config=c, devices_per_rank=4)
        yield cl, plane


def test_spmd_plane_put_get_roundtrip(spmd_cluster, rng):
    cl, plane = spmd_cluster
    ctx = cl.context(0, ici_plane=plane)
    h = ctx.alloc(16 << 10, OcmKind.REMOTE_DEVICE)
    assert h.rank == 1
    data = rng.integers(0, 256, 16 << 10, dtype=np.uint8)
    ctx.put(h, data)
    np.testing.assert_array_equal(np.asarray(ctx.get(h)), data)
    ctx.free(h)


@pytest.mark.parametrize("use_pallas", [False, True], ids=["ppermute", "pallas"])
def test_spmd_plane_one_sided_copy(spmd_cluster, rng, use_pallas):
    """ctx-level handle→handle copy rides the one-sided fabric — the
    analogue of ocm_copy between two RDMA allocations going straight to
    ib_write (/root/reference/src/lib.c:670-700). With use_pallas the
    transfer executes the remote-DMA kernel (interpret mode on CPU)."""
    cl, plane = spmd_cluster
    ctx0 = cl.context(0, ici_plane=plane)
    ctx1 = cl.context(1, ici_plane=plane)
    h_on_r0 = ctx1.alloc(16 << 10, OcmKind.REMOTE_DEVICE)
    h_on_r1 = ctx0.alloc(16 << 10, OcmKind.REMOTE_DEVICE)
    assert (h_on_r0.rank, h_on_r1.rank) == (0, 1)
    data = rng.integers(0, 256, 16 << 10, dtype=np.uint8)
    plane.put(h_on_r0, data)
    plane.copy(h_on_r1, h_on_r0, 16 << 10, use_pallas=use_pallas)
    np.testing.assert_array_equal(
        np.asarray(plane.get(h_on_r1, 16 << 10)), data
    )
    assert plane.stats["ici_copies"] == 1
    ctx0.free(h_on_r1)
    ctx1.free(h_on_r0)


def test_ctx_copy_remote_device_rides_ici(spmd_cluster, rng):
    """ctx.copy(dst, src) between two REMOTE_DEVICE handles must go through
    the plane's one-sided copy, not a host get→put round-trip."""
    cl, plane = spmd_cluster
    ctx0 = cl.context(0, ici_plane=plane)
    ctx1 = cl.context(1, ici_plane=plane)
    src = ctx1.alloc(16 << 10, OcmKind.REMOTE_DEVICE)   # lives on rank 0
    dst = ctx0.alloc(16 << 10, OcmKind.REMOTE_DEVICE)   # lives on rank 1
    data = rng.integers(0, 256, 16 << 10, dtype=np.uint8)
    ctx1.put(src, data)
    gets_before = plane.stats["gets"]
    ctx0.copy(dst, src)
    assert plane.stats["ici_copies"] == 1
    assert plane.stats["gets"] == gets_before  # no host round-trip
    np.testing.assert_array_equal(np.asarray(ctx0.get(dst)), data)
    ctx0.free(dst)
    ctx1.free(src)


def test_spmd_plane_typed_and_bounds(spmd_cluster):
    import jax.numpy as jnp

    cl, plane = spmd_cluster
    client = cl.client(0, ici_plane=plane)
    h = client.alloc(8 << 10, OcmKind.REMOTE_DEVICE)
    x = jnp.arange(2048, dtype=jnp.float32)
    client.put(h, x, 0)
    np.testing.assert_allclose(
        np.asarray(plane.get_as(h, (2048,), jnp.float32)), np.asarray(x)
    )
    with pytest.raises(ocm.OcmBoundsError):
        plane.get(h, (8 << 10) + 1, 0)
    with pytest.raises(ocm.OcmBoundsError):
        plane.put(h, np.zeros(16, np.uint8), (8 << 10) - 8)
    client.free(h)


# -- SpmdArena: the in-mesh fabric ---------------------------------------


def test_spmd_arena_host_put_get(rng):
    mesh = node_mesh()
    arena = sa.make_arena(mesh, 64 << 10)
    data = rng.integers(0, 256, 4096, dtype=np.uint8)
    arena = sa.host_put(arena, 3, data, 8192, mesh=mesh)
    got = np.asarray(sa.host_get(arena, 3, 4096, 8192, mesh=mesh))
    np.testing.assert_array_equal(got, data)
    # Other rows untouched.
    assert not np.any(np.asarray(sa.host_get(arena, 2, 4096, 8192, mesh=mesh)))


@pytest.mark.parametrize("use_pallas", [False, True], ids=["ppermute", "pallas"])
def test_spmd_arena_ici_copy(rng, use_pallas):
    mesh = node_mesh()
    arena = sa.make_arena(mesh, 64 << 10)
    data = rng.integers(0, 256, 4096, dtype=np.uint8)
    arena = sa.host_put(arena, 1, data, 0, mesh=mesh)
    arena = sa.ici_copy(
        arena, 1, 6, 0, 4096, 4096, mesh=mesh, use_pallas=use_pallas
    )
    got = np.asarray(sa.host_get(arena, 6, 4096, 4096, mesh=mesh))
    np.testing.assert_array_equal(got, data)
    # Source intact, sharding preserved.
    np.testing.assert_array_equal(
        np.asarray(sa.host_get(arena, 1, 4096, 0, mesh=mesh)), data
    )
    assert "node" in str(arena.sharding.spec)


def test_spmd_arena_ring_shift():
    mesh = node_mesh()
    d = mesh.devices.size
    arena = sa.make_arena(mesh, 8 << 10)
    for i in range(d):
        arena = sa.host_put(arena, i, np.full(512, i, np.uint8), 0, mesh=mesh)
    arena = sa.ring_shift(arena, 0, 512, mesh=mesh)
    for i in range(d):
        got = np.asarray(sa.host_get(arena, (i + 1) % d, 512, 0, mesh=mesh))
        assert np.all(got == i)
    # Reverse shift restores the original layout.
    arena = sa.ring_shift(arena, 0, 512, mesh=mesh, reverse=True)
    for i in range(d):
        got = np.asarray(sa.host_get(arena, i, 512, 0, mesh=mesh))
        assert np.all(got == i)


def test_spmd_arena_read_typed(rng):
    import jax.numpy as jnp

    mesh = node_mesh()
    arena = sa.make_arena(mesh, 64 << 10)
    x = rng.standard_normal((32, 16)).astype(np.float32)
    arena = sa.host_put(arena, 4, x, 4096, mesh=mesh)
    y = sa.read_typed(arena, 4, (32, 16), jnp.float32, 4096, mesh=mesh)
    np.testing.assert_allclose(np.asarray(y), x)


ALL_KINDS = [
    OcmKind.LOCAL_HOST, OcmKind.LOCAL_DEVICE,
    OcmKind.REMOTE_HOST, OcmKind.REMOTE_DEVICE,
]


@pytest.mark.parametrize("dst_kind", ALL_KINDS, ids=lambda k: k.name)
@pytest.mark.parametrize("src_kind", ALL_KINDS, ids=lambda k: k.name)
def test_full_copy_matrix(spmd_cluster, rng, src_kind, dst_kind):
    """ocm_copy across the FULL kind×kind matrix including both remote arms
    (the reference's 9-way dispatch covers host/GPU/RDMA/EXTOLL pairs,
    ocm_test.c:208-321 / lib.c:502-665): every pair composes through the
    context, with device×device riding the one-sided ICI fabric."""
    cl, plane = spmd_cluster
    ctx = cl.context(0, ici_plane=plane)
    n = 8 << 10
    src = ctx.alloc(n, src_kind)
    dst = ctx.alloc(n, dst_kind)
    data = rng.integers(0, 256, n, dtype=np.uint8)
    ctx.put(src, data)
    ctx.copy(dst, src)
    np.testing.assert_array_equal(np.asarray(ctx.get(dst)), data)
    # Source is untouched by the copy.
    np.testing.assert_array_equal(np.asarray(ctx.get(src)), data)
    ctx.free(src)
    ctx.free(dst)


def test_spmd_plane_concurrent_ops(spmd_cluster, rng):
    """Racing puts/gets/copies through the plane's donated-arena rebind:
    the per-plane mutex must serialize rebinds (a lost update or a
    dispatch on a deleted donated buffer fails this)."""
    import threading

    cl, plane = spmd_cluster
    ctx = cl.context(0, ici_plane=plane)
    handles = [ctx.alloc(4 << 10, OcmKind.REMOTE_DEVICE) for _ in range(4)]
    datas = [rng.integers(0, 256, 4 << 10, dtype=np.uint8) for _ in range(4)]
    errs = []

    def worker(i):
        try:
            for _ in range(6):
                plane.put(handles[i], datas[i])
                got = np.asarray(plane.get(handles[i], 4 << 10))
                np.testing.assert_array_equal(got, datas[i])
        except Exception as e:  # noqa: BLE001
            errs.append(f"t{i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive()
    assert not errs, errs
    for i, h in enumerate(handles):
        np.testing.assert_array_equal(
            np.asarray(plane.get(h, 4 << 10)), datas[i]
        )
        ctx.free(h)


def test_spmd_plane_mib_scale_pallas_copy(rng):
    """Handle-level one-sided copy at 1 MiB over 4 MiB rows through the
    remote-DMA route — the sizes that were CI-capped before the windowed
    interpret path (ops/pallas_ici.py): handle translation, daemon
    bookkeeping, and the DMA kernel all at realistic extents."""
    c = OcmConfig(host_arena_bytes=4 << 20, device_arena_bytes=4 << 20)
    with local_cluster(2, config=c, ndevices=4) as cl:
        plane = SpmdIciPlane(config=c, devices_per_rank=4)
        ctx0 = cl.context(0, ici_plane=plane)
        ctx1 = cl.context(1, ici_plane=plane)
        src = ctx1.alloc(1 << 20, OcmKind.REMOTE_DEVICE)  # on rank 0
        dst = ctx0.alloc(1 << 20, OcmKind.REMOTE_DEVICE)  # on rank 1
        data = rng.integers(0, 256, 1 << 20, dtype=np.uint8)
        plane.put(src, data)
        plane.copy(dst, src, 1 << 20, use_pallas=True)
        np.testing.assert_array_equal(
            np.asarray(plane.get(dst, 1 << 20)), data
        )
        ctx0.free(dst)
        ctx1.free(src)
