#!/bin/sh
# Two-daemon launch walkthrough — the reference's README:31-48 recipe
# (start a daemon per node from a nodefile, then run test programs
# against the live cluster), exercised here with BOTH daemon
# implementations at once: rank 0 native C++ (oncillamemd), rank 1
# Python — one wire protocol, interchangeable daemons.
#
# As written the script runs self-contained on ONE machine (both ranks
# on 127.0.0.1). For a real two-host deployment, write each host's name
# and reachable IP into the nodefile (see nodefile.sample), run ONE of
# the daemon lines below on each host (it finds its rank by hostname, or
# pass --rank), export OCM_BIND_HOST=0.0.0.0 so daemons accept
# cross-host connections, and point the app at any rank's daemon.
set -e
cd "$(dirname "$0")/.."
NATIVE=oncilla_tpu/runtime/native/build
NODEFILE=$(mktemp)
trap 'kill $D0 $D1 2>/dev/null; rm -f "$NODEFILE"' EXIT
cat > "$NODEFILE" <<EOF
0 localhost 127.0.0.1 7741
1 localhost 127.0.0.1 7742
EOF

# Build the native daemon + C client library once (cmake + ninja/make).
if [ ! -x "$NATIVE/oncillamemd" ]; then
  cmake -S oncilla_tpu/runtime/native -B "$NATIVE" >/dev/null
  cmake --build "$NATIVE" >/dev/null
fi

# Rank 0: the native C++ daemon (placement master).
"$NATIVE/oncillamemd" --nodefile "$NODEFILE" --rank 0 &
D0=$!
sleep 0.5
# Rank 1: the Python daemon, same protocol.
JAX_PLATFORMS=cpu python -m oncilla_tpu.runtime.daemon "$NODEFILE" --rank 1 &
D1=$!

# A pure-C application linked against libocm_tpu.so (the reference's
# ocm_test.c journey: init -> alloc -> one-sided put/get -> free).
# EXPECT_NNODES=2 makes the demo poll the master's membership until both
# daemons joined, then REQUIRE the allocation to be remote — a fixed
# sleep here raced the Python daemon's slow JAX import and silently
# demoted the "remote" leg to the local arm.
echo "== C app (ocm_c_demo) against the live cluster =="
LD_LIBRARY_PATH="$NATIVE" "$NATIVE/ocm_c_demo" "$NODEFILE" 0 1048576 2

# The same cluster from Python: remote alloc + push/pull via nodefile
# auto-attach.
echo "== Python app against the live cluster =="
JAX_PLATFORMS=cpu OCM_NODEFILE="$NODEFILE" python - <<'PY'
import numpy as np
import oncilla_tpu as ocm
from oncilla_tpu import OcmKind

ctx = ocm.ocm_init(ocm.OcmConfig(rank=0))
import time
for _ in range(300):  # joined membership, not nodefile size
    if ctx.status()["nnodes"] >= 2:
        break
    time.sleep(0.1)
else:
    raise SystemExit("cluster never reached 2 nodes")
h = ctx.alloc(1 << 20, OcmKind.REMOTE_HOST)
print(f"allocated {h.nbytes} B on rank {h.rank} (remote={h.is_remote})")
assert h.is_remote and h.rank == 1, "expected rank-1 remote placement"
data = np.random.default_rng(0).integers(0, 256, 1 << 20, dtype=np.uint8)
ctx.put(h, data)
assert np.array_equal(np.asarray(ctx.get(h)), data)
print("one-sided put/get roundtrip ok")
ctx.free(h)
ocm.ocm_tini(ctx)
PY
# Device kinds from pure C (the full taxonomy cross-process): a Python
# SPMD controller attaches with an ICI plane — auto-registering its
# plane endpoint — and the daemons relay the C app's one-sided
# device-kind ops to it (PLANE_PUT/PLANE_GET).
echo "== C app device-kind leg (daemon relay to the SPMD controller) =="
READY=$(mktemp -u)
JAX_PLATFORMS=cpu OCM_NODEFILE="$NODEFILE" OCM_READY_FILE="$READY" \
python - <<'PY' &
import os
import time

from oncilla_tpu.utils.platform import force_cpu_devices

# One plane row per cluster device: 2 ranks x 1 device each.
force_cpu_devices(2)
import oncilla_tpu as ocm
from oncilla_tpu.ops.ici import SpmdIciPlane
from oncilla_tpu.utils.config import OcmConfig

cfg = OcmConfig(rank=0)
plane = SpmdIciPlane(config=cfg, devices_per_rank=1)
ctx = ocm.ocm_init(cfg, ici_plane=plane)
open(os.environ["OCM_READY_FILE"], "w").write("ready")
print("controller: plane serving", flush=True)
time.sleep(120)  # killed by the script once the C leg finishes
PY
CTRL=$!
trap 'kill $D0 $D1 $CTRL 2>/dev/null || true; rm -f "$NODEFILE" "$READY"' EXIT
i=0
while [ ! -f "$READY" ] && [ $i -lt 300 ]; do
  kill -0 $CTRL 2>/dev/null || { echo "FAIL: controller died at startup"; exit 1; }
  sleep 0.1; i=$((i+1))
done
[ -f "$READY" ] || { echo "FAIL: controller never served its plane"; exit 1; }
LD_LIBRARY_PATH="$NATIVE" "$NATIVE/ocm_c_demo" "$NODEFILE" 0 262144 2 device
kill $CTRL 2>/dev/null || true
echo "== two-daemon walkthrough ok =="
