"""KV-cache paging through OCM handles: long-context decode whose KV pages
live anywhere in the pod — local HBM, a *remote* chip's HBM (ICI fabric), or
remote host DRAM (DCN fabric) — BASELINE.md config 5.

The decode working set stays small: a local tail window of the KV cache plus
a list of opaque OCM handles for completed pages. Attention over the full
context fetches pages back through the data plane. This is exactly the
reference's usage pattern (allocate remote, fill with ocm put, read back
with ocm get — test/ocm_test.c test 2) with a transformer as the
application.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from oncilla_tpu.core.handle import OcmAlloc
from oncilla_tpu.core.hbm import from_bytes, to_bytes
from oncilla_tpu.core.kinds import OcmKind
from oncilla_tpu.models.llama import LlamaConfig
from oncilla_tpu.utils.debug import GLOBAL_TRACER


@dataclass
class PagedKVCache:
    """KV pages for one decode session.

    ``backend`` is anything with alloc/free/put/get — an :class:`Ocm`
    context (local arms) or a :class:`ControlPlaneClient` (remote arms).
    Page layout: both K and V of one page are packed into a single
    allocation: (2, L, B, KV, page_tokens, Hd) bitcast to bytes.
    """

    backend: object
    cfg: LlamaConfig
    batch: int
    page_tokens: int = 128
    kind: OcmKind = OcmKind.REMOTE_DEVICE
    dtype: str = "float32"
    pages: list[OcmAlloc] = field(default_factory=list)

    @property
    def page_shape(self) -> tuple:
        c = self.cfg
        return (2, c.n_layers, self.batch, c.n_kv_heads, self.page_tokens,
                c.head_dim)

    @property
    def page_bytes(self) -> int:
        return int(np.prod(self.page_shape)) * jnp.dtype(self.dtype).itemsize

    @property
    def tokens_paged(self) -> int:
        return len(self.pages) * self.page_tokens

    def store_page(self, k_page: jax.Array, v_page: jax.Array) -> OcmAlloc:
        """Ship one completed page into the pod (one-sided put). k/v:
        (L, B, KV, page_tokens, Hd)."""
        packed = jnp.stack([k_page, v_page]).astype(jnp.dtype(self.dtype))
        assert packed.shape == self.page_shape, (packed.shape, self.page_shape)
        with GLOBAL_TRACER.span("kv_store_page", nbytes=self.page_bytes):
            h = self.backend.alloc(self.page_bytes, self.kind)
            self.backend.put(h, to_bytes(packed), 0)
        self.pages.append(h)
        return h

    def fetch_pages(self) -> tuple[jax.Array, jax.Array] | None:
        """Gather every page back (one-sided gets) and concatenate along the
        token axis: (L, B, KV, tokens_paged, Hd) x2."""
        if not self.pages:
            return None
        ks, vs = [], []
        with GLOBAL_TRACER.span(
            "kv_fetch_pages", nbytes=self.page_bytes * len(self.pages)
        ):
            for h in self.pages:
                raw = self.backend.get(h, self.page_bytes, 0)
                packed = from_bytes(
                    jnp.asarray(np.asarray(raw)), self.page_shape, self.dtype
                )
                ks.append(packed[0])
                vs.append(packed[1])
        return jnp.concatenate(ks, axis=3), jnp.concatenate(vs, axis=3)

    def free(self) -> None:
        for h in self.pages:
            self.backend.free(h)
        self.pages.clear()


def paged_decode_step(
    params: dict,
    token: jax.Array,
    pos: int,
    k_ctx: jax.Array | None,
    v_ctx: jax.Array | None,
    cfg: LlamaConfig,
):
    """Decode one token attending over the full valid context.

    k_ctx/v_ctx: (L, B, KV, T, Hd) — paged pages + local tail concatenated,
    containing exactly the T = ``pos`` valid entries (no masking needed);
    None when pos == 0. Returns (logits, (new_k, new_v)) where new_k/new_v
    are this token's (L, B, KV, 1, Hd) cache entries.

    Reuses :func:`llama.block` — one transformer-block implementation for
    training, cached decode, and paged decode.
    """
    from oncilla_tpu.models import llama

    x = params["embed"][token][:, None, :].astype(jnp.dtype(cfg.dtype))
    positions = jnp.asarray([pos])
    new_k, new_v = [], []

    for i in range(cfg.n_layers):
        def attend(q, kn, vn, i=i):
            new_k.append(kn)
            new_v.append(vn)
            if k_ctx is not None:
                k_all = jnp.concatenate(
                    [k_ctx[i].astype(q.dtype), kn.astype(q.dtype)], axis=2
                )
                v_all = jnp.concatenate(
                    [v_ctx[i].astype(q.dtype), vn.astype(q.dtype)], axis=2
                )
            else:
                k_all, v_all = kn.astype(q.dtype), vn.astype(q.dtype)
            return llama.grouped_attention(q, k_all, v_all)

        x = llama.block(cfg, x, llama.layer_params(params, i), positions, attend)

    logits = llama.final_logits(params, x, cfg)
    return logits[:, 0], (jnp.stack(new_k), jnp.stack(new_v))


class PagedDecoder:
    """A decode session whose KV history pages out through OCM.

    The local working set is one page of tail KV; every ``page_tokens``
    steps the tail ships into the pod (remote chip HBM / remote host DRAM
    per ``kind``) and decode continues against fetched pages + fresh tail —
    the Llama-KV-cache-in-remote-pod-HBM loop of BASELINE.md config 5.
    """

    def __init__(
        self,
        params: dict,
        cfg: LlamaConfig,
        backend,
        batch: int = 1,
        page_tokens: int = 16,
        kind: OcmKind = OcmKind.REMOTE_DEVICE,
        dtype: str = "float32",
    ):
        self.params = params
        self.cfg = cfg
        self.cache = PagedKVCache(
            backend, cfg, batch, page_tokens, kind, dtype
        )
        self.page_tokens = page_tokens
        self.pos = 0
        self._tail_k: list = []  # per-step (L, B, KV, 1, Hd)
        self._tail_v: list = []
        self._fetched = None  # concatenated paged context (k, v)

    def _context(self):
        parts_k, parts_v = [], []
        if self.cache.pages:
            if self._fetched is None:
                # Cold start (e.g. resuming a session): one bulk fetch.
                self._fetched = self.cache.fetch_pages()
            parts_k.append(self._fetched[0])
            parts_v.append(self._fetched[1])
        if self._tail_k:
            parts_k.append(jnp.concatenate(self._tail_k, axis=3))
            parts_v.append(jnp.concatenate(self._tail_v, axis=3))
        if not parts_k:
            return None, None
        return (
            jnp.concatenate(parts_k, axis=3),
            jnp.concatenate(parts_v, axis=3),
        )

    def step(self, token: jax.Array) -> jax.Array:
        k_ctx, v_ctx = self._context()
        logits, (nk, nv) = paged_decode_step(
            self.params, token, self.pos, k_ctx, v_ctx, self.cfg
        )
        self._tail_k.append(nk)
        self._tail_v.append(nv)
        self.pos += 1
        if len(self._tail_k) == self.page_tokens:
            # Ship the full tail into the pod; extend the local fetched
            # concat with the page we already hold instead of refetching
            # every page (keeps remote traffic O(pages), not O(pages^2)).
            k_page = jnp.concatenate(self._tail_k, axis=3).astype(
                jnp.dtype(self.cache.dtype)
            )
            v_page = jnp.concatenate(self._tail_v, axis=3).astype(
                jnp.dtype(self.cache.dtype)
            )
            self.cache.store_page(k_page, v_page)
            if self._fetched is None and len(self.cache.pages) > 1:
                self._fetched = self.cache.fetch_pages()
            elif self._fetched is None:
                self._fetched = (k_page, v_page)
            else:
                self._fetched = (
                    jnp.concatenate([self._fetched[0], k_page], axis=3),
                    jnp.concatenate([self._fetched[1], v_page], axis=3),
                )
            self._tail_k, self._tail_v = [], []
        return logits

    def close(self) -> None:
        self.cache.free()
