"""Cluster metrics history: in-process STATUS_PROM time series.

The prom families (:mod:`~oncilla_tpu.obs.prom`) are cumulative-only —
fine for an external Prometheus, useless on their own for "is the
cluster healthy RIGHT NOW". This module closes that gap without any
external scraper: a :class:`Scraper` polls every rank's STATUS_PROM
exposition (through whatever fetch callable the caller supplies —
``Ocm.fetch_prom`` in practice, so the poll rides the existing in-band
protocol and no new listener appears) and parses each sample into
fixed-size per-series rings held by a :class:`MetricsHistory`.

Over those rings the history can answer windowed questions locally:
counter deltas and rates (reset-aware, the ``increase()``/``rate()``
semantics), latest gauge values, and quantiles of the cumulative
histogram families via bucket-delta interpolation — everything the SLO
engine (:mod:`~oncilla_tpu.obs.slo`) needs to evaluate burn rates
in-process.

Stdlib-only by the obs-package contract.
"""

from __future__ import annotations

import os
import re
import threading
import time

from oncilla_tpu.obs import prom

# One scrape knob for the whole SLO stack: how often the background
# scraper polls each rank. Tolerant parse (watchdog.reload_threshold
# stance): a typo'd value degrades to the default, never crashes.
ENV_SCRAPE_S = "OCM_SLO_SCRAPE_S"
DEFAULT_SCRAPE_S = 2.0


def scrape_interval_s() -> float:
    try:
        return float(os.environ.get(ENV_SCRAPE_S, "") or DEFAULT_SCRAPE_S)
    except ValueError:
        return DEFAULT_SCRAPE_S


_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_UNESC = {r"\\": "\\", r"\"": '"', r"\n": "\n"}


def _unescape(v: str) -> str:
    out = v
    for esc, raw in _UNESC.items():
        out = out.replace(esc, raw)
    return out


def parse_samples(text: str) -> list[tuple[str, str, dict[str, str], float]]:
    """Parse one exposition into ``(family, sample_name, labels, value)``
    tuples. Runs :func:`prom.validate` first, so a malformed exposition
    raises instead of silently feeding garbage into the history — the
    same bar CI holds renderers to."""
    out: list[tuple[str, str, dict[str, str], float]] = []
    for family, lines in prom.validate(text).items():
        for line in lines:
            ex = prom._EXEMPLAR_RE.search(line)
            if ex is not None:
                line = line[: ex.start()]
            series, value = line.rsplit(" ", 1)
            name, _, rest = series.partition("{")
            labels = {
                k: _unescape(v)
                for k, v in _LABEL_RE.findall(rest.rstrip("}"))
            }
            out.append((family, name, labels, float(value)))
    return out


def _matches(labels: dict[str, str], want: dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in want.items())


class MetricsHistory:
    """Fixed-size time-series rings keyed by (sample name, label set).

    ``observe(rank, text)`` appends one scrape; the query side offers
    ``latest`` / ``delta`` / ``rate`` over matching series and
    ``hist_quantile`` over cumulative-histogram bucket deltas. All label
    matching is subset matching (match on the labels you name, ignore
    the rest), so one query naturally aggregates across ranks, ops, or
    engines unless the caller pins those labels."""

    def __init__(self, cap: int = 512) -> None:
        self.cap = int(cap)
        self._mu = threading.Lock()
        # (name, ((k,v)...)) -> list[(ts, value)] ring (newest last)
        self._series: dict[tuple, list[tuple[float, float]]] = {}
        self._family_of: dict[str, str] = {}  # sample name -> family
        self.scrapes = 0
        self.errors = 0

    # -- ingest ---------------------------------------------------------

    def observe_samples(
        self,
        samples: list[tuple[str, str, dict[str, str], float]],
        ts: float | None = None,
    ) -> None:
        ts = time.time() if ts is None else ts
        with self._mu:
            self.scrapes += 1
            for family, name, labels, value in samples:
                self._family_of[name] = family
                key = (name, tuple(sorted(labels.items())))
                ring = self._series.setdefault(key, [])
                ring.append((ts, value))
                if len(ring) > self.cap:
                    del ring[: len(ring) - self.cap]

    def observe(self, rank: int, text: str, ts: float | None = None) -> None:
        """Parse one rank's exposition into the rings. The ``rank``
        argument is advisory (every series already carries a ``rank``
        label); it exists so a fetch-failure path can still be counted
        against the right rank by the caller."""
        del rank
        self.observe_samples(parse_samples(text), ts=ts)

    def note_error(self) -> None:
        with self._mu:
            self.errors += 1

    # -- queries --------------------------------------------------------

    def series(
        self, name: str, **match: str
    ) -> dict[tuple, list[tuple[float, float]]]:
        """Matching rings, keyed by their full label tuple (a copy)."""
        want = {k: str(v) for k, v in match.items()}
        with self._mu:
            return {
                key: list(ring)
                for key, ring in self._series.items()
                if key[0] == name and _matches(dict(key[1]), want)
            }

    def latest(self, name: str, **match: str) -> float | None:
        """Sum of the newest value of every matching series (``None``
        when nothing matches — distinct from a genuine 0)."""
        rings = self.series(name, **match)
        if not rings:
            return None
        return sum(ring[-1][1] for ring in rings.values() if ring)

    @staticmethod
    def _ring_delta(ring: list[tuple[float, float]], since: float) -> float:
        """Counter increase across one ring's window, reset-aware: a
        sample below its predecessor restarts accumulation from zero
        (the restarted process's counter began at 0)."""
        win = [(t, v) for t, v in ring if t >= since]
        if len(win) < 2:
            return 0.0
        total = 0.0
        prev = win[0][1]
        for _, v in win[1:]:
            total += v - prev if v >= prev else v
            prev = v
        return total

    def delta(self, name: str, window_s: float,
              now: float | None = None, **match: str) -> float:
        """Summed counter increase over the trailing window across all
        matching series."""
        now = time.time() if now is None else now
        since = now - float(window_s)
        return sum(
            self._ring_delta(ring, since)
            for ring in self.series(name, **match).values()
        )

    def rate(self, name: str, window_s: float,
             now: float | None = None, **match: str) -> float:
        return self.delta(name, window_s, now=now, **match) / max(
            float(window_s), 1e-9
        )

    def hist_deltas(
        self,
        family: str,
        window_s: float,
        now: float | None = None,
        **match: str,
    ) -> dict[float, float]:
        """Per-``le`` cumulative bucket increases of a histogram family
        over the trailing window, aggregated across matching series.
        Keys are bucket bounds (``+Inf`` as ``float('inf')``); values
        stay cumulative, so ``by_le[inf]`` is the window's observation
        count."""
        now = time.time() if now is None else now
        since = now - float(window_s)
        by_le: dict[float, float] = {}
        for key, ring in self.series(family + "_bucket", **match).items():
            labels = dict(key[1])
            le_raw = labels.get("le")
            if le_raw is None:
                continue
            le = float("inf") if le_raw == "+Inf" else float(le_raw)
            by_le[le] = by_le.get(le, 0.0) + self._ring_delta(ring, since)
        return by_le

    def hist_quantile(
        self,
        family: str,
        q: float,
        window_s: float,
        now: float | None = None,
        **match: str,
    ) -> float | None:
        """Windowed quantile of a cumulative-histogram family: per-``le``
        bucket increases over the trailing window, aggregated across all
        matching series, then the classic linear interpolation inside
        the bucket holding the ``q``-th observation. ``None`` when no
        observations landed in the window."""
        by_le = self.hist_deltas(family, window_s, now=now, **match)
        if not by_le:
            return None
        les = sorted(by_le)
        total = by_le.get(float("inf"), max(by_le.values()))
        if total <= 0:
            return None
        target = max(0.0, min(1.0, q)) * total
        prev_le, prev_cum = 0.0, 0.0
        for le in les:
            cum = by_le[le]
            if cum >= target:
                if le == float("inf"):
                    return prev_le  # open-ended tail: best lower bound
                frac = (
                    (target - prev_cum) / (cum - prev_cum)
                    if cum > prev_cum else 1.0
                )
                return prev_le + frac * (le - prev_le)
            prev_le, prev_cum = le, cum
        return les[-2] if len(les) > 1 else None

    def families(self) -> dict[str, list[str]]:
        """Family -> sorted sample names seen (the live view's index)."""
        with self._mu:
            out: dict[str, list[str]] = {}
            for name, family in self._family_of.items():
                out.setdefault(family, []).append(name)
        return {fam: sorted(names) for fam, names in sorted(out.items())}

    def meta(self) -> dict:
        with self._mu:
            return {
                "series": len(self._series),
                "scrapes": self.scrapes,
                "errors": self.errors,
                "cap": self.cap,
            }


class Scraper:
    """Background poller: every ``interval_s`` it fetches each rank's
    STATUS_PROM text through ``fetch(rank)`` and feeds the history. A
    rank whose fetch raises is counted (``history.errors``) and skipped
    — a dead daemon must degrade the history, never kill the scraper
    (the SLO engine is often exactly what is watching for that death).
    """

    def __init__(
        self,
        fetch,
        ranks: list[int] | range,
        history: MetricsHistory | None = None,
        interval_s: float | None = None,
    ) -> None:
        self.fetch = fetch
        self.ranks = list(ranks)
        self.history = history if history is not None else MetricsHistory()
        self.interval_s = (
            scrape_interval_s() if interval_s is None else float(interval_s)
        )
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def poll_once(self, ts: float | None = None) -> int:
        """One synchronous sweep across all ranks; returns how many
        ranks scraped cleanly. The deterministic entry the SLO tests
        and one-shot CLI paths use instead of the thread."""
        ok = 0
        for rank in self.ranks:
            try:
                text = self.fetch(rank)
            except Exception:
                self.history.note_error()
                continue
            try:
                self.history.observe(rank, text, ts=ts)
                ok += 1
            except ValueError:
                self.history.note_error()
        return ok

    def start(self) -> "Scraper":
        if self._thread is not None:
            return self
        self._stop.clear()

        def _loop() -> None:
            while not self._stop.wait(self.interval_s):
                self.poll_once()

        self._thread = threading.Thread(
            target=_loop, name="ocm-slo-scraper", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
