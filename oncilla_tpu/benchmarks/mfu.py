"""Model FLOPs Utilization for the flagship model on one chip.

The judged single-chip compute metric: achieved matmul FLOP/s on the
flagship decoder divided by the chip's peak (bf16). The reference has no
analogue (it is a memory framework, SURVEY.md §0); the measurement shape
follows its benchmark idiom — N timed iterations of the hot loop after a
warm-up, excluded setup (test/ib_client.c:24 "excluded from timing").

FLOPs are counted analytically per matmul (2·m·n·k), not estimated with the
6·N·D rule, so GQA and the LM head are exact.
"""

from __future__ import annotations

import os
import time

import jax
import numpy as np

from oncilla_tpu.models.llama import LlamaConfig

# Peak dense bf16 FLOP/s per chip. v5e: 197 TFLOP/s (could be overridden
# for other generations via OCM_PEAK_TFLOPS).
PEAK_TFLOPS = float(os.environ.get("OCM_PEAK_TFLOPS", 197.0))


def forward_flops(cfg: LlamaConfig, batch: int, seq: int) -> int:
    """Exact matmul FLOPs of one forward pass (2mnk per matmul; elementwise
    and norms excluded — they are noise against the matmuls)."""
    b, s, d = batch, seq, cfg.dim
    hd = cfg.head_dim
    kv_dim = cfg.n_kv_heads * hd
    per_layer = (
        2 * b * s * d * d                 # Wq
        + 2 * 2 * b * s * d * kv_dim      # Wk, Wv
        + 2 * b * s * d * d               # Wo
        + 2 * 2 * b * cfg.n_heads * s * s * hd  # QK^T and PV
        + 3 * 2 * b * s * d * cfg.ffn_hidden    # gate, up, down
    )
    head = 2 * b * s * d * cfg.vocab
    return cfg.n_layers * per_layer + head


def train_flops(cfg: LlamaConfig, batch: int, seq: int) -> int:
    """Backward re-does ~2x the forward matmul work (grad wrt inputs and
    weights), so a train step is ~3x forward."""
    return 3 * forward_flops(cfg, batch, seq)


def chip_filling_config() -> tuple[LlamaConfig, int, int]:
    """~1.1B-param bf16 decoder + (batch, seq) sized for one v5e chip
    (16 GB HBM): ~2.3 GB of weights, long enough matmuls to saturate the
    MXU."""
    cfg = LlamaConfig(
        vocab=32000, dim=2048, n_layers=16, n_heads=16, n_kv_heads=8,
        ffn_hidden=8192, max_seq=2048, dtype="bfloat16",
    )
    return cfg, 8, 1024


def train_sized_config() -> tuple[LlamaConfig, int, int]:
    """The same ~1.1B flagship geometry as the forward measurement, batch
    sized down so params + grads + Adam moments (~4 weight copies) fit
    alongside activations. Measured on v5e: batch 4 gives 0.56 MFU; batch
    8 fails to compile (out of HBM), and a smaller ~0.4B model at batch 8
    reads lower (0.535) — bigger matmuls beat a bigger batch."""
    cfg, _, _ = chip_filling_config()
    return cfg, 4, 1024


def _sync(x) -> None:
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(x)[0].reshape(-1)[:8]))


def mfu_forward(
    cfg: LlamaConfig | None = None,
    batch: int | None = None,
    seq: int | None = None,
    steps: int = 10,
) -> dict:
    """Forward-pass MFU on the default device."""
    from oncilla_tpu.models import llama

    if cfg is None:
        cfg, batch, seq = chip_filling_config()
    # Host-side init: the jax.random path compiles one kernel per weight
    # shape (~1 min of wall time on a tunneled chip) and the exact init
    # values are irrelevant to a FLOP/s measurement.
    params = llama.init_params_host(0, cfg)
    tokens = jax.device_put(
        np.random.default_rng(0).integers(0, cfg.vocab, (batch, seq),
                                          dtype=np.int32)
    )

    @jax.jit
    def fwd(p, t):
        return llama.forward(p, t, cfg)

    out = fwd(params, tokens)
    _sync(out)  # compile + warm-up excluded from timing
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fwd(params, tokens)
    _sync(out)
    dt = time.perf_counter() - t0
    achieved = forward_flops(cfg, batch, seq) * steps / dt
    return {
        "mfu": achieved / (PEAK_TFLOPS * 1e12),
        "tflops": achieved / 1e12,
        "flops_per_step": forward_flops(cfg, batch, seq),
        "steps": steps,
        "seconds": dt,
    }


def mfu_train(
    cfg: LlamaConfig | None = None,
    batch: int | None = None,
    seq: int | None = None,
    steps: int = 6,
    remat=False,
    ce_block: int | None = None,
    mu_dtype=None,
    fold: bool = False,
) -> dict:
    """Train-step MFU (fwd + bwd + optimizer) on a single-device mesh.

    ``fold=True`` compiles all ``steps`` gradient steps into ONE dispatch
    (train.make_train_step(fold_steps=)) so the timed window contains no
    per-step host round-trips — on the tunneled dev chip each dispatch
    costs ~tens of ms, a harness artifact (~100 µs on a TPU VM) that
    deflates the unfolded measurement by several MFU points. Both
    flavors run the identical per-step math on the same fixed batch.

    Donation audit (VERDICT r3 item 6): params and opt_state are donated
    through the step (train._jit_step donate_argnums=(0, 1)) with output
    params pinned to the input specs, so XLA updates weights and Adam
    moments in place — no extra weight copies live across the step. The
    remaining knobs are ``remat`` ("dots" keeps matmul outputs, recomputes
    elementwise — batch can grow with ~zero extra MXU work), ``ce_block``
    (blocked vocab-head CE — no (B, S, V) logits tensor) and ``mu_dtype``
    (bf16 Adam µ — halves µ footprint+traffic, frees ~2 GB of HBM on the
    flagship so bigger batches fit WITHOUT paying the blocked-CE tax);
    :func:`mfu_train_best` sweeps them."""
    from oncilla_tpu.models import train

    if cfg is None:
        cfg, batch, seq = train_sized_config()
    mesh = train.make_mesh(1)
    # Host-side init (same rationale as mfu_forward); the optimizer is the
    # production one from train.py, so this measures the real train step.
    params, opt_state, tx = train.make_train_state_host(
        0, cfg, mesh, mu_dtype=mu_dtype
    )
    step = train.make_train_step(cfg, mesh, tx, use_ring=False,
                                 remat=remat, ce_block=ce_block,
                                 fold_steps=steps if fold else 0)
    rng = np.random.default_rng(0)
    tokens = jax.device_put(
        train.sample_batch(rng, cfg, batch, seq),
        jax.sharding.NamedSharding(mesh, train.data_spec()),
    )
    # TWO warm-up steps: the first compiles; the first call's donated
    # outputs come back with different buffer layouts than the freshly
    # device_put inputs, so the SECOND call compiles again for the
    # steady-state layouts (measured ~25 s each on v5e — one warm-up step
    # left a full compile inside the timed loop, reading 0.02 MFU for a
    # 0.31-MFU step).
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, tokens)
    _sync(params["wq"])
    t0 = time.perf_counter()
    if fold:
        # One dispatch contains all `steps` gradient steps.
        params, opt_state, loss = step(params, opt_state, tokens)
    else:
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, tokens)
    # Any output of the step executable works as the sync point (all
    # outputs of one jit call become ready together); params reads as the
    # clearer statement that the full update chain is being timed.
    _sync(params["wq"])
    dt = time.perf_counter() - t0
    achieved = train_flops(cfg, batch, seq) * steps / dt
    return {
        "mfu": achieved / (PEAK_TFLOPS * 1e12),
        "tflops": achieved / 1e12,
        "loss": float(loss),
        "steps": steps,
        "seconds": dt,
        "batch": batch,
        "remat": str(remat),
        "ce_block": ce_block,
        "mu_dtype": str(mu_dtype.__name__) if mu_dtype is not None else None,
        "fold": fold,
    }


def train_variants() -> list[dict]:
    """The ONE sweep grid, shared by :func:`mfu_train_best` and the
    recovery driver (examples/r5_recovery.sh) so the two can't drift.
    Expected-value-descending; see mfu_train_best for the rationale.
    ce_block never exceeds the effective sequence (seq-1 = 1023, padded
    to the block size): 1024 is one near-exact chunk; a 2048 block would
    pad HALF the chunk with masked positions and materialize MORE logits
    than the unblocked head it exists to avoid."""
    import jax.numpy as jnp

    _, batch4, _ = train_sized_config()
    bf16 = jnp.bfloat16
    return [
        # (the champion hypothesis: no CE-blocking tax, Adam amortized,
        # all timed steps folded into one dispatch so the tunnel's
        # per-dispatch latency — a harness artifact — is out of the
        # window; the unfolded twin right after quantifies that artifact)
        dict(batch=8, remat="dots", ce_block=None, mu_dtype=bf16, fold=True),
        dict(batch=8, remat="dots", ce_block=None, mu_dtype=bf16),
        dict(batch=16, remat="dots", ce_block=1024, mu_dtype=bf16, fold=True),
        dict(batch=batch4, remat=False, ce_block=None, mu_dtype=bf16, fold=True),
        dict(batch=16, remat="dots", ce_block=1024, mu_dtype=None),
        dict(batch=batch4, remat=False, ce_block=None, mu_dtype=None),  # r3 floor
        dict(batch=8, remat="dots", ce_block=1024, mu_dtype=None),      # r5 floor
        dict(batch=16, remat=True, ce_block=1024, mu_dtype=bf16),
    ]


def variant_label(v: dict) -> dict:
    """JSON-serializable form of a sweep-grid entry (mu_dtype by name,
    fold always present so folded/unfolded twins pair up in the banked
    variants table even on error/skip rows)."""
    return {
        **v,
        "mu_dtype": v["mu_dtype"].__name__ if v["mu_dtype"] else None,
        "fold": v.get("fold", False),
    }


def mfu_train_best(deadline: float | None = None) -> dict:
    """Sweep the memory-layout variants of the train step and keep the
    best MFU. The analytic FLOP count (3x forward) is identical for every
    variant, so wall time alone decides — a variant that recomputes more
    must win on time to win here.

    Variant order encodes what the r5 first-light measurements showed:
    batch 4 with UNBLOCKED CE (r3: 0.554) beats batch 8 with blocked CE
    (r5: 0.525-0.531) — the CE scan's small per-block head matmuls cost
    more MFU than batch-8's Adam amortization buys. So the leading
    hypothesis is batch 8 + dots-remat + *unblocked* CE, which only fits
    in 16 GB because bf16-µ (``mu_dtype``) frees ~2.2 GB of moment
    footprint; then the amortization ladder (batch 16 needs blocked CE
    again — its full logits don't fit at any µ dtype), then the measured
    incumbents as floors. With ``deadline`` (time.monotonic()), later
    variants are skipped once it passes; a variant that fails (e.g. OOM
    at compile) is recorded and skipped."""
    cfg, _, seq = train_sized_config()
    best, tried = None, []
    for v in train_variants():
        label = variant_label(v)
        if deadline is not None and time.monotonic() > deadline:
            tried.append({**label, "skipped": "deadline"})
            continue
        try:
            r = mfu_train(cfg, v["batch"], seq, remat=v["remat"],
                          ce_block=v["ce_block"], mu_dtype=v["mu_dtype"],
                          fold=v.get("fold", False))
        except Exception as e:  # noqa: BLE001 — an OOM variant is data
            tried.append({**label, "error": f"{type(e).__name__}"})
            continue
        tried.append(
            {k: r[k] for k in ("batch", "remat", "ce_block", "mu_dtype", "fold", "mfu")}
        )
        if best is None or r["mfu"] > best["mfu"]:
            best = r
    if best is None:
        raise RuntimeError(f"every mfu_train variant failed: {tried}")
    best["variants"] = tried
    return best
