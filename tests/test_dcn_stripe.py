"""Multi-stream striped DCN data plane: stripe planning, capability
negotiation (ACK coalescing), adaptive windowing, zero-copy get_into,
mid-stripe fault injection/retry, and per-transfer telemetry."""

import socket
import threading

import numpy as np
import pytest

from oncilla_tpu import OcmKind
from oncilla_tpu.fabric import tcp as tcp_mod
from oncilla_tpu.runtime import protocol as P
from oncilla_tpu.runtime.client import _PeerTuner
from oncilla_tpu.runtime.cluster import local_cluster
from oncilla_tpu.utils.config import MAX_CHUNK_BYTES, OcmConfig


def _cfg(**kw) -> OcmConfig:
    """Small-chunk config so a ~MiB transfer exercises multi-chunk,
    multi-stripe paths in milliseconds."""
    base = dict(
        host_arena_bytes=8 << 20,
        device_arena_bytes=1 << 20,
        chunk_bytes=64 << 10,
        inflight_ops=2,
        dcn_stripes=4,
        dcn_stripe_min_bytes=64 << 10,
        heartbeat_s=5.0,
    )
    base.update(kw)
    return OcmConfig(**base)


# -- config validation (the chunk_bytes / MAX_PAYLOAD satellite) ---------


def test_chunk_bytes_capped_at_wire_frame():
    # Regression: chunk_bytes up to 2^40 used to validate, then explode
    # as OcmProtocolError at pack time — a legal config must never encode
    # to a frame the peer rejects.
    with pytest.raises(ValueError, match="chunk_bytes"):
        OcmConfig(chunk_bytes=P.MAX_PAYLOAD)
    with pytest.raises(ValueError, match="chunk_bytes"):
        OcmConfig(chunk_bytes=1 << 40)
    cfg = OcmConfig(chunk_bytes=MAX_CHUNK_BYTES)
    assert cfg.chunk_bytes == MAX_CHUNK_BYTES


def test_max_chunk_frame_actually_fits():
    # The config cap and the wire cap must agree: a DATA_PUT carrying a
    # maximal chunk packs, and the slack covers the fixed fields.
    fixed = sum(
        {"q": 8, "Q": 8, "I": 4, "B": 1, "d": 8}[fmt]
        for _, fmt in P._SCHEMAS[P.MsgType.DATA_PUT]
    )
    assert MAX_CHUNK_BYTES + fixed <= P.MAX_PAYLOAD
    msg = P.Message(
        P.MsgType.DATA_PUT,
        {"alloc_id": 1, "offset": 0, "nbytes": MAX_CHUNK_BYTES},
        bytes(1),  # placeholder byte; length is what pack() checks
    )
    P.pack(msg)  # must not raise


def test_stripe_config_validated():
    with pytest.raises(ValueError, match="dcn_stripes"):
        OcmConfig(dcn_stripes=0)
    with pytest.raises(ValueError, match="dcn_stripe_min_bytes"):
        OcmConfig(dcn_stripe_min_bytes=0)


# -- stripe planning and the adaptive tuner ------------------------------


def test_plan_stripes_respects_min_bytes():
    cfg = _cfg(dcn_stripes=8, dcn_stripe_min_bytes=1 << 20)
    with local_cluster(2, config=cfg) as cluster:
        c = cluster.client(0, heartbeat=False)
        assert c._plan_stripes(512 << 10) == 1   # below one stripe's worth
        assert c._plan_stripes(2 << 20) == 2     # two stripes' worth
        assert c._plan_stripes(64 << 20) == 8    # capped by config


def test_tuner_grows_and_shrinks():
    cfg = _cfg(chunk_bytes=1 << 20, inflight_ops=2, dcn_adaptive=True)
    t = _PeerTuner(cfg)
    chunk0, win0 = t.plan()
    # Fast chunks at a rate that wants a deeper pipe: window steps up,
    # chunk doubles.
    t.observe(0.010, achieved_bps=1e9)
    chunk1, win1 = t.plan()
    assert chunk1 == chunk0 * 2
    assert win1 >= win0
    # Pathologically slow chunks: chunk halves (never below the floor).
    for _ in range(20):
        t.observe(1.0, achieved_bps=1e6)
    chunk2, _ = t.plan()
    assert chunk2 == _PeerTuner.MIN_CHUNK


def test_tuner_pinned_when_adaptive_off():
    cfg = _cfg(dcn_adaptive=False)
    t = _PeerTuner(cfg)
    t.observe(0.001, achieved_bps=1e9)
    t.observe(10.0, achieved_bps=1e3)
    assert t.plan() == (cfg.chunk_bytes, cfg.inflight_ops)


# -- striped transfers through a live cluster ----------------------------


def _roundtrip(cluster, nbytes: int, rng) -> tuple:
    client = cluster.client(0, heartbeat=False)
    h = client.alloc(nbytes, OcmKind.REMOTE_HOST)
    data = rng.integers(0, 256, nbytes, dtype=np.uint8)
    client.put(h, data)
    got = client.get(h, nbytes)
    return client, h, data, got


def test_striped_roundtrip_byte_exact(rng):
    with local_cluster(2, config=_cfg()) as cluster:
        client, h, data, got = _roundtrip(cluster, 2 << 20, rng)
        np.testing.assert_array_equal(got, data)
        # Striping actually engaged, and the Python daemon granted the
        # coalescing capability at the data-plane CONNECT probe.
        rec = client.tracer.transfers()[-2:]
        assert [r["op"] for r in rec] == ["put", "get"]
        assert rec[0]["stripes"] == 4 and rec[1]["stripes"] == 4
        assert rec[0]["coalesced"] is True   # put bursts coalesce
        assert rec[1]["coalesced"] is False  # get replies carry the data
        addr = client._owner_addr(h)
        assert client._dcn_caps[addr] & P.FLAG_CAP_COALESCE
        # Offset writes ride the same engine.
        client.put(h, data[: 256 << 10], offset=512 << 10)
        np.testing.assert_array_equal(
            client.get(h, 256 << 10, offset=512 << 10), data[: 256 << 10]
        )
        client.free(h)


def test_single_stream_path_selectable(rng):
    # OCM_DCN_STRIPES=1 (here: the config field it feeds) must keep the
    # original one-socket engine.
    with local_cluster(2, config=_cfg(dcn_stripes=1)) as cluster:
        client, h, data, got = _roundtrip(cluster, 1 << 20, rng)
        np.testing.assert_array_equal(got, data)
        assert client.tracer.transfers()[-1]["stripes"] == 1
        client.free(h)


def test_lockstep_fallback_when_coalesce_disabled(rng):
    with local_cluster(2, config=_cfg(dcn_coalesce=False)) as cluster:
        client, h, data, got = _roundtrip(cluster, 1 << 20, rng)
        np.testing.assert_array_equal(got, data)
        rec = client.tracer.transfers()[-2]
        assert rec["op"] == "put" and rec["coalesced"] is False
        assert client._dcn_caps[client._owner_addr(h)] & P.FLAG_CAP_COALESCE == 0
        client.free(h)


def test_get_into_reuses_caller_buffer(rng):
    with local_cluster(2, config=_cfg()) as cluster:
        client = cluster.client(0, heartbeat=False)
        h = client.alloc(1 << 20, OcmKind.REMOTE_HOST)
        data = rng.integers(0, 256, 1 << 20, dtype=np.uint8)
        client.put(h, data)
        out = np.zeros(1 << 20, dtype=np.uint8)
        ret = client.get_into(h, out)
        assert ret is out
        np.testing.assert_array_equal(out, data)
        with pytest.raises(ValueError, match="uint8"):
            client.get_into(h, np.zeros(4, np.float32))
        client.free(h)


def test_context_get_out_param(rng):
    with local_cluster(2, config=_cfg()) as cluster:
        ctx = cluster.context(0, heartbeat=False)
        h = ctx.alloc(1 << 20, OcmKind.REMOTE_HOST)
        data = rng.integers(0, 256, 1 << 20, dtype=np.uint8)
        ctx.put(h, data)
        out = np.zeros(1 << 20, dtype=np.uint8)
        assert ctx.get(h, out=out) is out
        np.testing.assert_array_equal(out, data)
        ctx.free(h)


# -- mid-stripe fault injection ------------------------------------------


@pytest.mark.parametrize("stripes", [1, 4])
@pytest.mark.parametrize("direction", ["put", "get"])
def test_mid_stripe_socket_kill_retries(rng, monkeypatch, stripes, direction):
    """Kill the leased socket mid-stripe: the stripe's retry path must
    re-lease and complete byte-exactly, and a failed stripe must not
    corrupt sibling stripes' destination ranges."""
    kill_type = (
        P.MsgType.DATA_PUT if direction == "put" else P.MsgType.DATA_GET
    )
    with local_cluster(2, config=_cfg(dcn_stripes=stripes)) as cluster:
        client = cluster.client(0, heartbeat=False)
        nbytes = 2 << 20
        h = client.alloc(nbytes, OcmKind.REMOTE_HOST)
        data = rng.integers(0, 256, nbytes, dtype=np.uint8)
        if direction == "get":
            client.put(h, data)  # stage content before the faulty get

        # The stripe loops live in fabric/tcp.py (the engine's PR-7
        # re-homing); the fault must be injected at that seam.
        real_send = tcp_mod.send_msg
        fired = []
        lock = threading.Lock()

        def flaky(sock, msg):
            if msg.type == kill_type:
                with lock:
                    first = not fired
                    if first:
                        fired.append(1)
                if first:
                    # Simulate the peer dropping the leased connection
                    # mid-pipeline.
                    sock.shutdown(socket.SHUT_RDWR)
            return real_send(sock, msg)

        monkeypatch.setattr(tcp_mod, "send_msg", flaky)
        if direction == "put":
            client.put(h, data)
            got = client.get(h, nbytes)
        else:
            got = client.get(h, nbytes)
        monkeypatch.setattr(tcp_mod, "send_msg", real_send)
        assert fired, "fault was never injected"
        np.testing.assert_array_equal(got, data)
        # The retry is visible in the transfer record.
        recs = [r for r in client.tracer.transfers() if r["op"] == direction]
        assert recs[-1]["retries"] >= 1
        client.free(h)


def test_failed_stripe_does_not_corrupt_siblings(rng, monkeypatch):
    """A stripe that dies on its FIRST attempt must leave sibling
    stripes' already-landed destination views intact (disjoint ranges)."""
    with local_cluster(2, config=_cfg(dcn_stripes=4)) as cluster:
        client = cluster.client(0, heartbeat=False)
        nbytes = 2 << 20
        h = client.alloc(nbytes, OcmKind.REMOTE_HOST)
        data = rng.integers(0, 256, nbytes, dtype=np.uint8)
        client.put(h, data)

        real_recv = tcp_mod.recv_msg
        state = {"n": 0}
        lock = threading.Lock()

        def flaky_recv(sock, *a, **kw):
            # Kill one stripe's socket after a few replies landed.
            with lock:
                state["n"] += 1
                kill = state["n"] == 3
            if kill:
                sock.shutdown(socket.SHUT_RDWR)
            return real_recv(sock, *a, **kw)

        monkeypatch.setattr(tcp_mod, "recv_msg", flaky_recv)
        got = client.get(h, nbytes)
        monkeypatch.setattr(tcp_mod, "recv_msg", real_recv)
        np.testing.assert_array_equal(got, data)
        client.free(h)


def test_stale_owner_addr_falls_back_to_membership(rng):
    """A cached owner_addr pointing at a dead port (owner daemon
    restarted elsewhere) must fall back to the membership table for the
    stripe-set lease itself, not just for mid-stripe failures."""
    with local_cluster(2, config=_cfg()) as cluster:
        client = cluster.client(0, heartbeat=False)
        nbytes = 1 << 20
        h = client.alloc(nbytes, OcmKind.REMOTE_HOST)
        # Poison the cached data-plane address with a port nothing serves.
        dead = socket.socket()
        dead.bind(("127.0.0.1", 0))
        dead_port = dead.getsockname()[1]
        dead.close()
        h.owner_addr = ("127.0.0.1", dead_port)
        data = rng.integers(0, 256, nbytes, dtype=np.uint8)
        client.put(h, data)  # multi-stripe: lease_set fallback engages
        np.testing.assert_array_equal(client.get(h, nbytes), data)
        assert h.owner_addr == (
            cluster.entries[h.rank].connect_host, cluster.entries[h.rank].port
        )
        client.free(h)


# -- protocol-level burst hygiene ----------------------------------------


def test_interleaved_request_inside_burst_rejected():
    """A non-DATA_PUT frame inside an open FLAG_MORE burst is a protocol
    violation: the daemon must answer BAD_MSG, not desync."""
    with local_cluster(2, config=_cfg()) as cluster:
        client = cluster.client(0, heartbeat=False)
        h = client.alloc(256 << 10, OcmKind.REMOTE_HOST)
        owner = cluster.entries[h.rank]
        s = socket.create_connection((owner.connect_host, owner.port))
        try:
            P.send_msg(s, P.Message(
                P.MsgType.DATA_PUT,
                {"alloc_id": h.alloc_id, "offset": 0, "nbytes": 1024},
                bytes(1024),
                flags=P.FLAG_MORE,
            ))
            P.send_msg(s, P.Message(P.MsgType.STATUS, {}))
            r = P.recv_msg(s)
            assert r.type == P.MsgType.ERROR
            assert r.fields["code"] == int(P.ErrCode.BAD_MSG)
        finally:
            s.close()
        client.free(h)


def test_coalesced_burst_error_reported_once():
    """A burst whose chunks fail (bad alloc) must produce exactly ONE
    ERROR reply at burst end."""
    with local_cluster(2, config=_cfg()) as cluster:
        client = cluster.client(0, heartbeat=False)
        h = client.alloc(256 << 10, OcmKind.REMOTE_HOST)
        owner = cluster.entries[h.rank]
        s = socket.create_connection((owner.connect_host, owner.port))
        try:
            for i in range(3):
                P.send_msg(s, P.Message(
                    P.MsgType.DATA_PUT,
                    {"alloc_id": 999999, "offset": i * 1024, "nbytes": 1024},
                    bytes(1024),
                    flags=P.FLAG_MORE if i < 2 else 0,
                ))
            r = P.recv_msg(s)
            assert r.type == P.MsgType.ERROR
            assert r.fields["code"] == int(P.ErrCode.BAD_ALLOC_ID)
            # The connection is still in sync: a follow-up valid exchange
            # works on the same socket.
            P.send_msg(s, P.Message(P.MsgType.STATUS, {}))
            assert P.recv_msg(s).type == P.MsgType.STATUS_OK
        finally:
            s.close()
        client.free(h)


# -- telemetry surfaced through STATUS -----------------------------------


def test_status_reports_data_plane_throughput(rng):
    with local_cluster(2, config=_cfg()) as cluster:
        client, h, data, got = _roundtrip(cluster, 1 << 20, rng)
        np.testing.assert_array_equal(got, data)
        # Client-side ring: every record carries the full telemetry shape.
        st = client.status()
        recs = st["dcn_client"]["transfers"]
        assert recs, "no client transfer records"
        for rec in recs:
            assert {
                "op", "bytes", "seconds", "gbps", "stripes", "window",
                "chunk_bytes", "retries", "coalesced",
            } <= set(rec)
        last_put = [r for r in recs if r["op"] == "put"][-1]
        assert last_put["bytes"] == 1 << 20 and last_put["gbps"] > 0
        # Daemon-side: the owner daemon's STATUS carries served-op stats
        # (JSON data tail of STATUS_OK).
        owner_st = client.status(rank=h.rank)
        assert "dcn" in owner_st, owner_st.keys()
        assert "dcn_put_srv" in owner_st["dcn"]["ops"]
        assert owner_st["dcn"]["ops"]["dcn_put_srv"]["total_bytes"] >= 1 << 20
        # Coalesced put bursts land in the daemon's transfer ring too.
        assert any(
            t["op"] == "put_srv" and t["coalesced"]
            for t in owner_st["dcn"]["transfers"]
        )
        client.free(h)


def test_status_fields_keep_v2_shape(rng):
    # The original STATUS_OK fixed fields survive alongside the tail.
    with local_cluster(2, config=_cfg()) as cluster:
        client = cluster.client(0, heartbeat=False)
        st = client.status()
        for k in ("rank", "nnodes", "live_allocs", "host_bytes_live",
                  "device_bytes_live"):
            assert k in st


# -- concurrent striped transfers share the pool safely ------------------


def test_concurrent_striped_transfers(rng):
    """Two threads striping to the same owner at once: the stripe sets
    degrade gracefully under the pool cap and both transfers stay
    byte-exact."""
    with local_cluster(2, config=_cfg()) as cluster:
        client = cluster.client(0, heartbeat=False)
        n = 1 << 20
        handles = [client.alloc(n, OcmKind.REMOTE_HOST) for _ in range(2)]
        datas = [rng.integers(0, 256, n, dtype=np.uint8) for _ in range(2)]
        errs = []

        def mover(i):
            try:
                client.put(handles[i], datas[i])
                got = client.get(handles[i], n)
                np.testing.assert_array_equal(got, datas[i])
            except Exception as e:
                errs.append(e)

        ts = [threading.Thread(target=mover, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs
        for h in handles:
            client.free(h)
