"""Tiered KV page store: HBM -> local host arena -> remote arenas.

The storage half of the serving scenario (ROADMAP item 1): fixed-size KV
pages live in exactly one of three tiers —

- ``HOT``  — device HBM extents (``core/hbm.py``'s DeviceArena through an
  :class:`~oncilla_tpu.core.context.Ocm` LOCAL_DEVICE handle). Lit
  opportunistically: on the CPU fallback the arena is a jax CPU buffer
  and the tier stays byte-faithful (BENCH r03-r05: the TPU tunnel stays
  wedged in this container); if the device arena cannot take a page the
  store degrades that allocation to WARM instead of failing.
- ``WARM`` — this host's DRAM arena (``core/hostmem.py``, LOCAL_HOST).
- ``COLD`` — remote arenas over the existing striped/fabric/mux data
  plane (REMOTE_HOST through a ``ControlPlaneClient`` — or, when the
  store runs without a control plane, a LOCAL_HOST stand-in flagged
  ``cold_sim`` so a benchmark can never mistake loopback for DCN).
- ``FROZEN`` — disk, via an attached :class:`~oncilla_tpu.persist.
  FrozenStore` (``frozen_backend``). The fourth rung (ROADMAP item 5):
  watermark demotion spills COLD victims to CRC-trailed extent files
  instead of destroying them, and a persisted prefix cache restores
  from the same store on warm boot. No backend attached (the default)
  = the tier has zero capacity and every code path is byte-identical
  to the three-tier store.

Movement is **watermark-driven**: each bounded tier demotes LRU pages to
the next tier down when occupancy crosses its high watermark, down to
its low watermark — the same high/low discipline as the daemon reaper's
``_pressure_evict``. Promotion reads through the PR-3 registered-
receive-buffer path (``get(out=)`` / ``get_into``): the store keeps one
page-sized staging buffer and every fetch lands in it, never in a fresh
allocation.

The QoS mapping (PR 6): tiers correspond to priority classes —
``TIER_PRIORITY`` maps HOT/WARM/COLD onto PRIO_HIGH/PRIO_NORMAL/
PRIO_LOW. A deployment gives the cold-tier client a PRIO_LOW profile at
CONNECT, so when a remote owner runs hot the daemon-side evictor and
this store agree on who goes first: cold serving pages are the
preferred victims everywhere. Within the store the serving-side evictor
enforces the matching invariant — a **shared** extent (prefix-cache
page with live references) is never victimized while referenced, just
as ``_pressure_evict`` never takes an active above-low entry.
"""

from __future__ import annotations

import enum
import itertools
import threading
from dataclasses import dataclass, field

import numpy as np

from oncilla_tpu.core.errors import (
    OcmError,
    OcmInvalidHandle,
    OcmOutOfMemory,
)
from oncilla_tpu.core.handle import OcmAlloc
from oncilla_tpu.core.kinds import OcmKind
from oncilla_tpu.obs import journal as obs_journal
from oncilla_tpu.qos.policy import PRIO_HIGH, PRIO_LOW, PRIO_NORMAL
from oncilla_tpu.serving.metrics import ServingStats
from oncilla_tpu.utils.debug import printd


class Tier(enum.Enum):
    HOT = "hbm"
    WARM = "host"
    COLD = "remote"
    FROZEN = "frozen"


#: The PR-6 QoS mapping: what priority class each tier's allocations
#: should declare at CONNECT, so daemon-side pressure eviction and the
#: serving-side evictor enforce one policy. FROZEN shares PRIO_LOW with
#: COLD: both are the preferred victims; FROZEN is just the rung where
#: "victim" stops meaning "destroyed".
TIER_PRIORITY = {
    Tier.HOT: PRIO_HIGH,
    Tier.WARM: PRIO_NORMAL,
    Tier.COLD: PRIO_LOW,
    Tier.FROZEN: PRIO_LOW,
}

_ORDER = (Tier.HOT, Tier.WARM, Tier.COLD, Tier.FROZEN)


@dataclass(frozen=True)
class FrozenPageHandle:
    """Handle for a FROZEN-resident page: the store key of its extent
    file (no arena offset exists — disk is addressed by name)."""

    key: str


@dataclass
class Page:
    """One KV page: fixed-size bytes living in exactly one tier."""

    page_id: int
    nbytes: int
    tier: Tier
    handle: OcmAlloc
    last_use: int = 0
    pins: int = 0
    #: Prefix-cache references (cross-tenant sharing). A page with
    #: ``shared`` set and ``refs > 0`` is immutable and unevictable.
    shared: bool = False
    refs: int = 0
    #: Bumped on every rewrite; stale prefetched bytes are discarded on
    #: version mismatch.
    version: int = 0
    freed: bool = field(default=False, compare=False)


class TieredPageStore:
    """Fixed-page-size store over three tiers with watermark demotion.

    Single-writer discipline: all tier *mutation* (alloc/promote/demote/
    free) happens on the engine thread; prefetch workers only ever fetch
    bytes (:meth:`fetch_bytes` is read-only and thread-safe), and the
    engine installs the result. ``stats`` mutation is internally locked.
    """

    def __init__(
        self,
        ctx,
        page_bytes: int,
        hot_capacity: int = 8,
        warm_capacity: int = 16,
        cold_backend=None,
        high_pct: int = 90,
        low_pct: int = 70,
        stats: ServingStats | None = None,
        frozen_backend=None,
        cold_capacity: int | None = None,
    ):
        self.ctx = ctx
        self.page_bytes = int(page_bytes)
        # COLD is unbounded in the three-tier store (it is the floor);
        # with a frozen backend attached it must be finite or nothing
        # would ever spill to disk. FROZEN with no backend has zero
        # capacity: every pre-persist code path is untouched.
        if cold_capacity is None:
            cold_capacity = (
                (1 << 30) if frozen_backend is None
                else max(2 * int(warm_capacity), 1)
            )
        self.capacity = {Tier.HOT: int(hot_capacity),
                         Tier.WARM: int(warm_capacity),
                         Tier.COLD: int(cold_capacity),
                         Tier.FROZEN: (1 << 30) if frozen_backend is not None
                         else 0}
        self.high_pct = high_pct
        self.low_pct = low_pct
        self.cold_backend = cold_backend
        self.frozen_backend = frozen_backend
        #: True when COLD is simulated in the local host arena (no
        #: control plane attached): benchmarks must label the cell.
        self.cold_sim = cold_backend is None
        # Ephemeral frozen page keys continue past any leftover
        # ``page-N`` files from a prior run so a stale extent is never
        # silently overwritten by an unrelated page.
        frz_start = 0
        if frozen_backend is not None:
            for k in frozen_backend.keys():
                if k.startswith("page-"):
                    try:
                        frz_start = max(frz_start, int(k[5:]))
                    except ValueError:
                        pass
        self._frz_ids = itertools.count(frz_start + 1)
        self.stats = stats or ServingStats()
        self.pages: dict[int, Page] = {}
        self._ids = itertools.count(1)
        self._clock = itertools.count(1)
        # The registered receive buffer for tier moves (PR-3 get(out=)):
        # one page-sized staging window reused by every engine-thread
        # fetch. Prefetch workers bring their own (serving/engine.py).
        self._recvbuf = np.empty(self.page_bytes, dtype=np.uint8)
        self._mu = threading.Lock()

    # -- tier backends ----------------------------------------------------

    def _alloc_in(self, tier: Tier) -> OcmAlloc:
        if tier == Tier.HOT:
            return self.ctx.alloc(self.page_bytes, OcmKind.LOCAL_DEVICE)
        if tier == Tier.WARM:
            return self.ctx.alloc(self.page_bytes, OcmKind.LOCAL_HOST)
        if tier == Tier.FROZEN:
            if self.frozen_backend is None:
                raise OcmError("no frozen backend attached")
            if not self.frozen_backend.has_room(self.page_bytes):
                raise OcmOutOfMemory("frozen store budget exhausted")
            return FrozenPageHandle(f"page-{next(self._frz_ids)}")
        if self.cold_backend is not None:
            return self.cold_backend.alloc(self.page_bytes,
                                           OcmKind.REMOTE_HOST)
        return self.ctx.alloc(self.page_bytes, OcmKind.LOCAL_HOST)

    def _free_handle(self, tier: Tier, handle: OcmAlloc) -> None:
        if tier == Tier.FROZEN:
            self.frozen_backend.delete(handle.key)
        elif tier == Tier.COLD and self.cold_backend is not None:
            self.cold_backend.free(handle)
        else:
            self.ctx.free(handle)

    def _put(self, tier: Tier, handle: OcmAlloc, data: np.ndarray) -> None:
        if tier == Tier.FROZEN:
            self.frozen_backend.write(
                handle.key, np.asarray(data).tobytes(), meta={"kind": "page"}
            )
        elif tier == Tier.COLD and self.cold_backend is not None:
            self.cold_backend.put(handle, data, 0)
            self.stats.note_remote(data.nbytes, inbound=False)
        else:
            self.ctx.put(handle, data, 0)

    def _get(self, tier: Tier, handle: OcmAlloc, nbytes: int,
             out: np.ndarray | None):
        """Read a page's bytes, landing in ``out`` when given (the
        registered-receive path: ``get_into`` on the DCN leg, ``get(out=)``
        through the context)."""
        if tier == Tier.FROZEN:
            # A slow CRC-verified read; OcmFrozenCorrupt propagates
            # typed — a corrupt extent is refused, never served.
            raw = np.frombuffer(
                self.frozen_backend.read_bytes(handle.key), dtype=np.uint8
            )
            if out is not None:
                out[:nbytes] = raw[:nbytes]
                return out[:nbytes]
            return raw[:nbytes].copy()
        if tier == Tier.COLD and self.cold_backend is not None:
            if out is not None:
                get_into = getattr(self.cold_backend, "get_into", None)
                if get_into is not None:
                    res = get_into(handle, out[:nbytes], 0)
                else:
                    res = out
                    out[:nbytes] = np.asarray(
                        self.cold_backend.get(handle, nbytes, 0)
                    ).view(np.uint8).reshape(-1)
            else:
                res = self.cold_backend.get(handle, nbytes, 0)
            self.stats.note_remote(nbytes, inbound=True)
            return np.asarray(res).view(np.uint8).reshape(-1)[:nbytes]
        if out is not None:
            return np.asarray(
                self.ctx.get(handle, out=out[:nbytes])
            ).reshape(-1)
        raw = self.ctx.get(handle, nbytes, 0)
        return np.asarray(raw).view(np.uint8).reshape(-1)[:nbytes]

    # -- occupancy --------------------------------------------------------

    def _live(self, tier: Tier) -> list[Page]:
        return [p for p in self.pages.values() if p.tier == tier]

    def occupancy(self) -> dict:
        out = {}
        for t in _ORDER:
            live = self._live(t)
            out[t.value] = {"pages": len(live),
                            "bytes": sum(p.nbytes for p in live)}
        return out

    def _sync_stats(self) -> None:
        occ = self.occupancy()
        self.stats.set_occupancy(
            {k: v["pages"] for k, v in occ.items()},
            {k: v["bytes"] for k, v in occ.items()},
        )

    # -- page lifecycle ---------------------------------------------------

    def touch(self, page: Page) -> None:
        page.last_use = next(self._clock)

    def _check_live(self, page: Page) -> None:
        if page.freed or page.page_id not in self.pages:
            raise OcmInvalidHandle(f"use of freed page {page.page_id}")

    def alloc_page(self, data, shared: bool = False,
                   prefer: Tier = Tier.HOT) -> Page:
        """Store one page of bytes, preferring ``prefer`` and degrading
        down-tier when the preferred arena is full (HBM lit
        opportunistically), then enforce watermarks."""
        raw = np.ascontiguousarray(np.asarray(data)).view(
            np.uint8).reshape(-1)
        if raw.nbytes != self.page_bytes:
            raise ValueError(
                f"page is {raw.nbytes} B, store built for {self.page_bytes}"
            )
        start = _ORDER.index(prefer)
        last_err: Exception | None = None
        for tier in _ORDER[start:]:
            # LRU residents demote to make room for the newcomer; if
            # nothing is demotable (all pinned / referenced-shared) the
            # newcomer degrades a tier instead — never the residents.
            self._make_room(tier)
            if len(self._live(tier)) >= self.capacity[tier]:
                continue
            try:
                handle = self._alloc_in(tier)
            except OcmError as e:  # arena full / remote BUSY: degrade a tier
                last_err = e
                printd("serving: %s tier alloc degraded: %s", tier.value, e)
                continue
            self._put(tier, handle, raw)
            page = Page(next(self._ids), self.page_bytes, tier, handle,
                        shared=shared)
            self.touch(page)
            self.pages[page.page_id] = page
            self.enforce_watermarks()
            self._sync_stats()
            return page
        raise OcmError(
            f"no tier can take a page (last error: {last_err})"
        )

    def read_page(self, page: Page, out: np.ndarray | None = None
                  ) -> np.ndarray:
        """The page's bytes (registered-receive into ``out`` when given;
        else into the store's staging buffer for non-hot tiers)."""
        self._check_live(page)
        self.touch(page)
        if out is None and page.tier != Tier.HOT:
            out = self._recvbuf
        return self._get(page.tier, page.handle, page.nbytes, out)

    def write_page(self, page: Page, data) -> None:
        """Rewrite a page in place. Forbidden on a referenced shared
        page — that is what :meth:`cow` is for (a write would corrupt
        every other tenant's context)."""
        self._check_live(page)
        if page.shared and page.refs > 0:
            raise OcmInvalidHandle(
                f"write to shared page {page.page_id} with {page.refs} "
                "live reference(s); copy-on-write first"
            )
        raw = np.ascontiguousarray(np.asarray(data)).view(
            np.uint8).reshape(-1)
        if raw.nbytes != page.nbytes:
            raise ValueError(f"page write of {raw.nbytes} B into "
                             f"{page.nbytes} B page")
        self._put(page.tier, page.handle, raw)
        page.version += 1
        self.touch(page)

    def cow(self, page: Page) -> Page:
        """Copy-on-write: a private copy of a (typically shared) page,
        placed by the normal tier policy. The original — and every other
        tenant's view of it — is untouched."""
        self._check_live(page)
        data = self.read_page(page)
        clone = self.alloc_page(np.array(data, copy=True), shared=False)
        self.stats.note_cow()
        obs_journal.record("page_cow", src=page.page_id,
                           dst=clone.page_id, nbytes=page.nbytes)
        return clone

    def free_page(self, page: Page) -> None:
        if page.freed:
            return
        if page.shared and page.refs > 0:
            raise OcmInvalidHandle(
                f"free of shared page {page.page_id} with {page.refs} "
                "live reference(s)"
            )
        del self.pages[page.page_id]
        page.freed = True
        self._free_handle(page.tier, page.handle)
        self._sync_stats()

    def close(self) -> None:
        """Free every live page (shared ones included: teardown)."""
        for page in list(self.pages.values()):
            page.refs = 0
            self.free_page(page)

    # -- movement ---------------------------------------------------------

    def _move(self, page: Page, to: Tier,
              data: np.ndarray | None = None) -> None:
        """Relocate a page's bytes between tiers. ``data`` short-cuts
        the read when the caller already fetched the current version
        (prefetch); it must be version-checked by the caller."""
        if page.tier == to:
            return
        if data is None:
            data = self.read_page(page)
        try:
            new_handle = self._alloc_in(to)
        except OcmError as e:
            # Opportunistic tier: a full target arena cancels the move,
            # never the page.
            printd("serving: move of page %d to %s declined: %s",
                   page.page_id, to.value, e)
            return
        self._put(to, new_handle, np.asarray(data))
        with self._mu:
            old_tier, old_handle = page.tier, page.handle
            page.tier, page.handle = to, new_handle
            # Any relocation invalidates in-flight prefetched bytes: a
            # worker mid-read of the OLD extent (freed and scrubbed
            # below) must see its version check fail at install time.
            page.version += 1
        self._free_handle(old_tier, old_handle)
        promote = _ORDER.index(to) < _ORDER.index(old_tier)
        self.stats.note_move(promote)
        obs_journal.record(
            "page_promote" if promote else "page_demote",
            page_id=page.page_id, src=old_tier.value, dst=to.value,
            nbytes=page.nbytes, shared=page.shared, refs=page.refs,
        )
        self._sync_stats()

    def promote(self, page: Page, to: Tier = Tier.HOT,
                data: np.ndarray | None = None,
                version: int | None = None) -> None:
        """Move a page up-tier (the page-fault / prefetch-install path).
        ``data``+``version`` come from a prefetch worker; a version
        mismatch (the page was rewritten since the fetch was issued)
        discards the stale bytes and re-reads."""
        self._check_live(page)
        if version is not None and version != page.version:
            data = None
        if _ORDER.index(to) >= _ORDER.index(page.tier):
            return
        # Make room FIRST so the promotion itself cannot bounce off a
        # full target tier.
        self._make_room(to)
        self._move(page, to, data=data)
        self.touch(page)
        self.enforce_watermarks()

    def promote_many(self, items, to: Tier = Tier.HOT) -> None:
        """Batched promotion for one fused decode tick: ``items`` is an
        iterable of ``(page, data, version)`` (data/version as in
        :meth:`promote`, None for a plain fault). Room is made and pages
        move one at a time (the single-writer discipline is unchanged),
        but the watermark sweep runs ONCE at the end instead of once per
        page — a B-session batch build does O(1) sweeps, not O(B)."""
        moved = False
        for page, data, version in items:
            self._check_live(page)
            if version is not None and version != page.version:
                data = None
            if _ORDER.index(to) >= _ORDER.index(page.tier):
                continue
            self._make_room(to)
            self._move(page, to, data=data)
            self.touch(page)
            moved = True
        if moved:
            self.enforce_watermarks()

    def demote(self, page: Page, to: Tier) -> None:
        self._check_live(page)
        if _ORDER.index(to) <= _ORDER.index(page.tier):
            return
        self._move(page, to)

    def pin(self, page: Page) -> None:
        page.pins += 1

    def unpin(self, page: Page) -> None:
        page.pins = max(0, page.pins - 1)

    # -- watermark eviction ----------------------------------------------

    def _victims(self, tier: Tier) -> list[Page]:
        """Demotion candidates, LRU-first. NEVER a pinned page, and
        NEVER a shared extent while referenced — the serving-side twin
        of the reaper's never-an-active-above-low guarantee."""
        return sorted(
            (p for p in self._live(tier)
             if p.pins == 0 and not (p.shared and p.refs > 0)),
            key=lambda p: p.last_use,
        )

    def _make_room(self, tier: Tier) -> None:
        """Demote until ``tier`` has a free slot (promotion headroom)."""
        nxt = {Tier.HOT: Tier.WARM, Tier.WARM: Tier.COLD}.get(tier)
        if tier == Tier.COLD and self.frozen_backend is not None:
            nxt = Tier.FROZEN
        if nxt is None:
            return
        while len(self._live(tier)) >= self.capacity[tier]:
            victims = self._victims(tier)
            if not victims:
                return  # everything pinned/referenced: overshoot allowed
            self._make_room(nxt)
            self._move(victims[0], nxt)

    def enforce_watermarks(self) -> None:
        """High/low watermark demotion per bounded tier, exactly the
        daemon reaper's ``_pressure_evict`` shape: past high, demote
        LRU victims down to low. With a frozen backend attached, COLD is
        bounded too and spills to disk — the demote-to-FROZEN leg."""
        pairs = [(Tier.HOT, Tier.WARM), (Tier.WARM, Tier.COLD)]
        if self.frozen_backend is not None:
            pairs.append((Tier.COLD, Tier.FROZEN))
        for tier, nxt in pairs:
            cap = self.capacity[tier]
            # Floor at one page: integer watermark math on a tiny tier
            # must never read "demote everything, always".
            high = max(cap * self.high_pct // 100, 1)
            low = max(cap * self.low_pct // 100, 1)
            if len(self._live(tier)) <= high:
                continue
            for victim in self._victims(tier):
                if len(self._live(tier)) <= low:
                    break
                self._move(victim, nxt)

    # -- prefetch support -------------------------------------------------

    def fetch_bytes(self, page: Page, out: np.ndarray) -> tuple[int, bool]:
        """Thread-safe read of a page's bytes into the caller's
        registered buffer (prefetch workers): returns (version, ok).
        Read-only — tier installation happens on the engine thread via
        :meth:`promote`."""
        with self._mu:
            if page.freed:
                return (page.version, False)
            tier, handle, version = page.tier, page.handle, page.version
        try:
            self._get(tier, handle, page.nbytes, out)
        except OcmError:
            return (version, False)
        return (version, True)
