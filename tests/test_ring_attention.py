"""Ring attention vs dense attention: exactness on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from oncilla_tpu.parallel.mesh import node_mesh
from oncilla_tpu.parallel.ring_attention import (
    ring_attention, ring_attention_shard,
)


def dense_attention(q, k, v, causal):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        S = q.shape[2]
        mask = jnp.tril(jnp.ones((S, S), dtype=bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(rng, causal):
    mesh = node_mesh()
    B, H, S, D = 2, 4, 64, 32  # S = 8 chunks x 8
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), dtype=jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), dtype=jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), dtype=jnp.float32)

    want = dense_attention(q, k, v, causal)
    got = ring_attention(q, k, v, mesh, axis_name="node", causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_inside_jit(rng):
    mesh = node_mesh()
    B, H, S, D = 1, 2, 32, 16
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), dtype=jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), dtype=jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), dtype=jnp.float32)

    @jax.jit
    def f(q, k, v):
        return ring_attention(q, k, v, mesh, axis_name="node", causal=True)

    got = f(q, k, v)
    want = dense_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_grad_finite(rng):
    mesh = node_mesh()
    B, H, S, D = 1, 2, 16, 8
    q = jnp.asarray(rng.standard_normal((B, H, S, D)), dtype=jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, S, D)), dtype=jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, S, D)), dtype=jnp.float32)

    def loss(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, mesh, axis_name="node", causal=True) ** 2
        )

    g = jax.grad(loss)(q, k, v)
    assert np.all(np.isfinite(np.asarray(g)))
    # Gradient matches the dense implementation.
    def dense_loss(q, k, v):
        return jnp.sum(dense_attention(q, k, v, True) ** 2)

    gd = jax.grad(dense_loss)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gd), atol=1e-4)


def test_ring_window_non_causal_rejected():
    with pytest.raises(ValueError, match="causal"):
        ring_attention_shard(None, None, None, axis_name="sp",
                            causal=False, window=4)
