"""Grade a banked bench JSON against the round-5 targets.

The judged perf claims each have a concrete bar (VERDICT r4 "do this"
1-4); this turns a ``BENCH_SELF_r0N.json`` / ``BENCH_r0N.json`` line into
pass/fail verdicts so a late tunnel recovery needs zero analysis lag:

    python -m oncilla_tpu.benchmarks.check BENCH_SELF_r05.json
"""

from __future__ import annotations

import json
import sys


def grade(doc: dict) -> list[tuple[str, str, str]]:
    """Returns (target, verdict, evidence) rows; verdict in
    PASS / FAIL / NO DATA."""
    d = doc.get("detail", {})
    rows: list[tuple[str, str, str]] = []

    def row(name, ok, evidence):
        rows.append((name, "NO DATA" if ok is None else
                     ("PASS" if ok else "FAIL"), evidence))

    # 1. Headline copy bandwidth vs the 0.80 x 819 GB/s target.
    v = doc.get("value", 0.0)
    row("headline copy >= target (vs_baseline >= 1.0)",
        None if not v else doc.get("vs_baseline", 0.0) >= 1.0,
        f"value={v} GB/s vs_baseline={doc.get('vs_baseline')}")

    # 2. GB-read leg within 2x of the DMA copy figure (r4 weak #1: the
    #    row-kernel routing's first hardware run must land hundreds of
    #    GB/s, not r3's 14).
    sweep = d.get("gb_sweep") or {}
    pallas = d.get("pallas_gbps")

    def best_read(legs):
        """Amortized routed-DMA leg when present (legs[2]), else the
        per-op leg — per-op timing on a tunneled dev chip measures the
        ~70 ms dispatch round-trip, not the engine (sweep.py leg
        semantics)."""
        if not isinstance(legs, list):
            return None
        if len(legs) > 2 and legs[2]:
            return legs[2]
        return legs[1] if len(legs) > 1 else None

    read_1g = None
    for size, legs in sweep.items():
        if str(size) in ("1073741824", "1g", "1G"):
            read_1g = best_read(legs)
    if read_1g is None and sweep:
        # Largest size present.
        try:
            k = max((s for s in sweep if str(s).isdigit()), key=int)
            read_1g = best_read(sweep[k])
        except (ValueError, TypeError):
            read_1g = None
    row("GB-sweep read leg >= pallas_gbps / 2",
        None if read_1g is None or not pallas else read_1g >= pallas / 2,
        f"read={read_1g} GB/s pallas={pallas} GB/s")

    # 3. Ceiling probe ran (closes or caps the 655.2 target with data).
    #    -1 marks a probe leg skipped by the stage deadline — partial
    #    evidence is NO DATA (rerun with more budget), not a failure.
    ceil = d.get("ceiling") or {}
    complete = ceil and all(
        ceil.get(k, -1) not in (None, -1)
        for k in ("read_only_gbps", "vmem_roundtrip_gbps")
    )
    row("ceiling probe banked (read_only + stream sweep)",
        True if complete else None,
        json.dumps(ceil) if ceil else "absent")

    # 4. Train MFU >= 0.60 (r4 "do this" #4).
    mfu_t = d.get("mfu_train")
    row("mfu_train >= 0.60", None if mfu_t is None else mfu_t >= 0.60,
        f"mfu_train={mfu_t} variants={len(d.get('mfu_train_variants') or [])}")

    # 5. Page-fused paged decode >= plain decode tok/s.
    kv = d.get("kv_decode_tok_s") or {}
    fused, plain = kv.get("device_fused"), kv.get("plain")
    row("paged device_fused >= plain tok/s",
        None if fused is None or plain is None else fused >= plain,
        f"device_fused={fused} plain={plain}")

    # 6. DCN daemon-path bandwidth recorded (config 2; chip-free).
    dcn = d.get("dcn") or {}
    row("dcn banked and verified",
        None if not dcn else bool(dcn.get("verified")),
        json.dumps(dcn) if dcn else "absent")
    return rows


def main(argv: list[str]) -> int:
    path = argv[1] if len(argv) > 1 else "BENCH_SELF_r05.json"
    doc = json.loads(open(path).read().strip().splitlines()[-1])
    rows = grade(doc)
    width = max(len(r[0]) for r in rows)
    for name, verdict, evidence in rows:
        print(f"{name:<{width}}  {verdict:<8}  {evidence}")
    return 0 if all(v != "FAIL" for _, v, _ in rows) else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
