#!/bin/sh
# Self-contained multi-"host" walkthrough: 2 oncilla daemons + 2 JAX
# processes forming ONE global SPMD mesh (jax.distributed over Gloo on
# CPU here; identical driver code on a real multi-host TPU slice), the
# shared train step running over it, and a train-state checkpoint written
# into rank 1's arena and read back one-sided by every process.
# Usage: multihost_train.sh [PORT0 PORT1 COORD_PORT] — override the
# defaults to run concurrent instances (the test passes free ports).
set -e
cd "$(dirname "$0")/.."
PORT0=${1:-7745}
PORT1=${2:-7746}
COORD=${3:-7799}
NODEFILE=$(mktemp)
trap 'kill $D0 $D1 $P1 2>/dev/null || true; rm -f "$NODEFILE"' EXIT
cat > "$NODEFILE" <<EOF
0 localhost 127.0.0.1 $PORT0
1 localhost 127.0.0.1 $PORT1
EOF

JAX_PLATFORMS=cpu python -m oncilla_tpu.runtime.daemon "$NODEFILE" --rank 0 &
D0=$!
JAX_PLATFORMS=cpu python -m oncilla_tpu.runtime.daemon "$NODEFILE" --rank 1 &
D1=$!

python examples/multihost_train.py 1 2 $COORD "$NODEFILE" &
P1=$!
python examples/multihost_train.py 0 2 $COORD "$NODEFILE"
wait $P1
echo "== multihost walkthrough ok =="
