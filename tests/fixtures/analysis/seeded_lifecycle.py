"""Seeded handle-lifecycle violations for the analyzer's own tests.

Scanned explicitly by tests/test_lifecycle.py (the fixtures directory is
excluded from default tree walks); never imported. Each seeded_* function
must yield exactly one finding of its rule; each ok_* function documents
an exemption and must stay silent.
"""

import pytest

from oncilla_tpu.core.errors import OcmInvalidHandle
from oncilla_tpu.core.context import ocm_init


# -- seeded violations (one finding each) -------------------------------


def seeded_leak_on_branch(ctx, cond):
    h = ctx.alloc(4096)
    if cond:
        ctx.free(h)
    # fall-through path reaches function exit with h still live


def seeded_leak_on_raise(ctx, n):
    h = ctx.alloc(n)
    if n > 4096:
        raise ValueError("too big")  # exception edge out of a try-less body
    ctx.free(h)


def seeded_use_after_free(ctx):
    h = ctx.alloc(64)
    ctx.free(h)
    ctx.put(h, b"x")


def seeded_double_free(ctx):
    h = ctx.alloc(64)
    ctx.free(h)
    ctx.free(h)


def seeded_discarded_alloc(ctx):
    ctx.alloc(128)


# -- exemptions (silent) ------------------------------------------------


def ok_free_on_every_path(ctx, cond):
    h = ctx.alloc(64)
    if cond:
        ctx.free(h)
    else:
        ctx.free(h)


def ok_escape_by_return(ctx):
    h = ctx.alloc(64)
    return h


def ok_escape_by_store(registry, ctx, cond):
    h = ctx.alloc(64)
    if cond:
        ctx.free(h)
        return
    registry["h"] = h


class OkHolder:
    def __init__(self, ctx):
        self.h = ctx.alloc(64)

    def stash(self, ctx, cond):
        h = ctx.alloc(64)
        if cond:
            ctx.free(h)
        else:
            self.h = h


def ok_expected_error_is_exempt(ctx):
    h = ctx.alloc(64)
    ctx.free(h)
    with pytest.raises(OcmInvalidHandle):
        ctx.free(h)  # the runtime rejecting a double free IS the test


def ok_reassignment_kills_tracking(ctx):
    h = ctx.alloc(64)
    ctx.free(h)
    h = ctx.alloc(64)
    ctx.put(h, b"y")
    ctx.free(h)


def ok_with_ocm_init_releases(cond):
    with ocm_init() as ctx:
        h = ctx.alloc(64)
        if cond:
            ctx.free(h)
        # __exit__ -> tini() reclaims every live handle


def ok_tini_releases(ctx, cond):
    h = ctx.alloc(64)
    if cond:
        ctx.free(h)
    ctx.tini()


def ok_try_finally_covers_raise(ctx, risky):
    h = ctx.alloc(64)
    try:
        if risky:
            raise RuntimeError("op failed")
    finally:
        ctx.free(h)


def ok_suppressed(ctx):
    ctx.alloc(64)  # ocm-lint: allow[handle-leak-on-path] — reaper fixture
