"""Seeded violation: unbounded relay cycle (rpcgraph ``relay-cycle``).

Scanned explicitly by tests/test_rpcgraph.py — excluded from default
``python -m oncilla_tpu.analysis`` walks (lint.iter_py_files skips
``fixtures`` directories). The GOSSIP handler re-sends its own type to a
peer with no terminal-flag guard and no hop decrement — the PR-8
heartbeat-amplification shape. Exactly ONE ``relay-cycle`` finding.
"""


class MsgType:
    GOSSIP = 1
    GOSSIP_OK = 2


def Message(msgtype, fields, flags=0):
    return (msgtype, fields, flags)


def _on_gossip(msg, peers, host, port):
    # Forwards its own type verbatim-equivalent with nothing to stop a
    # peer's handler doing the same right back: GOSSIP -> GOSSIP.
    peers.request(host, port, Message(MsgType.GOSSIP, {"seq": 1}))  # FINDING
    return Message(MsgType.GOSSIP_OK, {})


_HANDLERS = {
    MsgType.GOSSIP: _on_gossip,
}
