// Socket plumbing shared by the daemon and the C client library
// (conn_put/conn_get analogue, /root/reference/src/sock.c): length-exact
// framed send/recv of protocol.hh messages over blocking TCP, plus dial().

#pragma once

#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>
#include <string>
#include <vector>

#include "protocol.hh"

namespace ocm {

inline void send_all(int fd, const uint8_t* p, size_t n) {
  while (n) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) throw ProtocolError("send failed");
    p += w;
    n -= size_t(w);
  }
}

// Read exactly n bytes. eof_ok permits a clean EOF *before the first
// byte* (returns false); EOF mid-read always throws (protocol.py
// _recv_exact semantics). Socket errors (r < 0) are reported with errno —
// a reset from a crashed peer is not "malformed input".
inline bool recv_all(int fd, uint8_t* p, size_t n, bool eof_ok = false) {
  size_t want = n;
  while (want) {
    ssize_t r = ::recv(fd, p, want, 0);
    if (r < 0)
      throw ProtocolError(std::string("recv failed: ") + strerror(errno));
    if (r == 0) {
      if (eof_ok && want == n) return false;
      throw ProtocolError(want == n ? "peer closed" : "peer closed mid-message");
    }
    p += r;
    want -= size_t(r);
  }
  return true;
}

// Scatter-gather sendall of [a, b] without concatenating them — the
// bulk-data path (copying an 8 MiB payload into a contiguous frame costs
// two extra memcpys per chunk).
inline void send_vec(int fd, const uint8_t* a, size_t an, const uint8_t* b,
                     size_t bn) {
  while (an + bn) {
    struct iovec iov[2];
    int cnt = 0;
    if (an) iov[cnt++] = {const_cast<uint8_t*>(a), an};
    if (bn) iov[cnt++] = {const_cast<uint8_t*>(b), bn};
    struct msghdr mh = {};
    mh.msg_iov = iov;
    mh.msg_iovlen = size_t(cnt);
    ssize_t w = ::sendmsg(fd, &mh, MSG_NOSIGNAL);
    if (w <= 0) throw ProtocolError("send failed");
    size_t ww = size_t(w);
    size_t from_a = ww < an ? ww : an;
    a += from_a;
    an -= from_a;
    ww -= from_a;
    b += ww;
    bn -= ww;
  }
}

inline void send_msg(int fd, const Message& m) {
  if (m.data.size() >= (64u << 10)) {
    auto prefix = pack_prefix(m);
    send_vec(fd, prefix.data(), prefix.size(), m.data.data(), m.data.size());
    return;
  }
  auto buf = pack(m);
  send_all(fd, buf.data(), buf.size());
}

// With `scratch`, small payloads land in a REUSED buffer, and BULK
// payloads of fixed-field messages (DATA_PUT/DATA_GET_OK chunks) are
// received STRAIGHT into Message::data — no intermediate buffer, no
// extra copy per 8 MiB chunk. Pass one scratch per connection in the
// data-plane loops.
inline Message recv_msg(int fd, std::vector<uint8_t>* scratch = nullptr) {
  uint8_t header[kHeaderSize];
  if (!recv_all(fd, header, kHeaderSize, /*eof_ok=*/true))
    throw ProtocolError("peer closed");
  uint64_t plen = 0;
  for (int i = 0; i < 4; ++i) plen |= uint64_t(header[8 + i]) << (8 * i);
  if (plen > kMaxPayload) throw ProtocolError("advertised payload too large");
  size_t ffix = SIZE_MAX;
  if (plen >= (64u << 10)) {
    try {
      ffix = fixed_fields_size(MsgType(header[5]));
    } catch (const ProtocolError&) {
      ffix = SIZE_MAX;  // unknown type: let unpack raise the real error
    }
  }
  if (ffix != SIZE_MAX && ffix <= 64 && plen >= ffix &&
      (plen - ffix) >= (64u << 10)) {
    uint8_t fields[64];
    if (ffix) recv_all(fd, fields, ffix);
    Message m = unpack_fields(header, fields, ffix);
    m.data.resize(plen - ffix);
    recv_all(fd, m.data.data(), m.data.size());
    return m;
  }
  if (scratch) {
    if (scratch->size() < plen) scratch->resize(plen);
    if (plen) recv_all(fd, scratch->data(), plen);
    return unpack(header, scratch->data(), plen);
  }
  std::vector<uint8_t> payload(plen);
  if (plen) recv_all(fd, payload.data(), plen);
  return unpack(header, payload.data(), plen);
}

inline int dial(const std::string& host, int port) {
  struct addrinfo hints = {};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  if (getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints, &res))
    throw ProtocolError("resolve failed for " + host);
  int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0 || ::connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    freeaddrinfo(res);
    if (fd >= 0) ::close(fd);
    throw ProtocolError("connect failed to " + host + ":" +
                        std::to_string(port));
  }
  freeaddrinfo(res);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  // Large buffers so 8 MiB pipelined chunks stream without window
  // stalls (kernel may clamp; best effort).
  int buf = 4 << 20;
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
  return fd;
}

}  // namespace ocm
