"""Single-node context tests: the ocm_test.c test-1/test-3 analogues for the
local arms (allocation lifecycle ×3 per kind, reference test/ocm_test.c:32-130;
kind×kind copy matrix, ocm_test.c:208-321)."""

import jax.numpy as jnp
import numpy as np
import pytest

import oncilla_tpu as ocm
from oncilla_tpu import OcmKind


@pytest.fixture
def ctx():
    cfg = ocm.OcmConfig(host_arena_bytes=8 << 20, device_arena_bytes=8 << 20)
    c = ocm.ocm_init(cfg)
    yield c
    c.tini()


LOCAL_KINDS = [OcmKind.LOCAL_HOST, OcmKind.LOCAL_DEVICE]


@pytest.mark.parametrize("kind", LOCAL_KINDS)
def test_lifecycle_three_iterations(ctx, kind):
    # Mirrors ocm_test.c test 1: alloc → localbuf → introspect → free, ×3.
    for _ in range(3):
        h = ctx.alloc(4096, kind)
        assert not h.freed
        buf = ctx.localbuf(h)
        assert buf is not None and len(buf) == 4096
        assert ocm.ocm_is_remote(h) is False
        assert ocm.ocm_alloc_kind(h) == kind
        assert ocm.ocm_remote_sz(h) == 0
        ctx.free(h)
        assert h.freed


@pytest.mark.parametrize("kind", LOCAL_KINDS)
def test_put_get_pattern(ctx, rng, kind):
    # Pattern-stamp + readback compare (idiom of ib_client.c:164-179).
    h = ctx.alloc(8192, kind)
    data = rng.integers(0, 256, size=8192, dtype=np.uint8)
    ctx.put(h, data)
    out = np.asarray(ctx.get(h, 8192))
    np.testing.assert_array_equal(out, data)
    ctx.free(h)


@pytest.mark.parametrize("kind", LOCAL_KINDS)
def test_put_get_with_offset(ctx, rng, kind):
    h = ctx.alloc(4096, kind)
    data = rng.integers(0, 256, size=1024, dtype=np.uint8)
    ctx.put(h, data, offset=512)
    out = np.asarray(ctx.get(h, 1024, offset=512))
    np.testing.assert_array_equal(out, data)
    ctx.free(h)


@pytest.mark.parametrize("kind", LOCAL_KINDS)
def test_bounds_checked(ctx, kind):
    # post_send bounds-check analogue (rdma.c:55-59).
    h = ctx.alloc(1024, kind)
    with pytest.raises(ocm.OcmBoundsError):
        ctx.put(h, np.zeros(2048, np.uint8))
    with pytest.raises(ocm.OcmBoundsError):
        ctx.get(h, 100, offset=1000)
    ctx.free(h)


def test_typed_roundtrip(ctx):
    h = ctx.alloc(4 * 256, OcmKind.LOCAL_DEVICE)
    x = jnp.arange(256, dtype=jnp.float32)
    ctx.put(h, x)
    y = ctx.get_as(h, (256,), jnp.float32)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x))
    ctx.free(h)


@pytest.mark.parametrize("src_kind", LOCAL_KINDS)
@pytest.mark.parametrize("dst_kind", LOCAL_KINDS)
def test_copy_matrix(ctx, rng, src_kind, dst_kind):
    # ocm_copy across every local kind pair (ocm_test.c test 3).
    src = ctx.alloc(2048, src_kind)
    dst = ctx.alloc(2048, dst_kind)
    data = rng.integers(0, 256, size=2048, dtype=np.uint8)
    ctx.put(src, data)
    ctx.copy(dst, src)
    np.testing.assert_array_equal(np.asarray(ctx.get(dst)), data)
    ctx.free(src)
    ctx.free(dst)


def test_copy_same_device_offsets(ctx, rng):
    src = ctx.alloc(4096, OcmKind.LOCAL_DEVICE)
    dst = ctx.alloc(4096, OcmKind.LOCAL_DEVICE)
    data = rng.integers(0, 256, size=1024, dtype=np.uint8)
    ctx.put(src, data, offset=256)
    ctx.copy(dst, src, nbytes=1024, dst_offset=512, src_offset=256)
    np.testing.assert_array_equal(np.asarray(ctx.get(dst, 1024, offset=512)), data)


def test_use_after_free_rejected(ctx):
    h = ctx.alloc(1024)
    ctx.free(h)
    with pytest.raises(ocm.OcmInvalidHandle):
        ctx.put(h, np.zeros(16, np.uint8))
    with pytest.raises(ocm.OcmInvalidHandle):
        ctx.free(h)


def test_remote_without_control_plane_rejected(ctx):
    with pytest.raises(ocm.OcmConnectError):
        ctx.alloc(1024, OcmKind.REMOTE_DEVICE)


def test_copy_onesided_parity(ctx, rng):
    h = ctx.alloc(1024, OcmKind.LOCAL_HOST)
    data = rng.integers(0, 256, size=1024, dtype=np.uint8)
    ocm.ocm_copy_onesided(ctx, h, data, "write")
    out = ocm.ocm_copy_onesided(ctx, h, data, "read")
    np.testing.assert_array_equal(out, data)


def test_arena_reuse_many_allocs(ctx):
    # Churn: allocate/free loops must not leak arena space.
    for _ in range(50):
        hs = [ctx.alloc(64 << 10, k) for k in LOCAL_KINDS for _ in range(4)]
        for h in hs:
            ctx.free(h)
    assert ctx.host_arena.allocator.bytes_live == 0
    assert ctx.device_arenas[0].allocator.bytes_live == 0


def test_ocm_copy_out_in_named_api():
    # The reference declares ocm_copy_out/ocm_copy_in but ships -1 stubs
    # (/root/reference/src/lib.c:491-499); here they are working one-sided
    # read/write wrappers.
    import numpy as np

    import oncilla_tpu as ocm
    from oncilla_tpu import OcmKind

    ctx = ocm.ocm_init(
        ocm.OcmConfig(host_arena_bytes=4 << 20, device_arena_bytes=4 << 20)
    )
    try:
        data = np.random.default_rng(0).integers(
            0, 256, 1 << 16, dtype=np.uint8
        )
        for kind in (OcmKind.LOCAL_HOST, OcmKind.LOCAL_DEVICE):
            h = ctx.alloc(1 << 16, kind)
            ocm.ocm_copy_in(ctx, h, data)
            np.testing.assert_array_equal(
                np.asarray(ocm.ocm_copy_out(ctx, h)), data
            )
            # offset round trip
            ocm.ocm_copy_in(ctx, h, data[:1024], offset=2048)
            np.testing.assert_array_equal(
                np.asarray(ocm.ocm_copy_out(ctx, h, nbytes=1024, offset=2048)),
                data[:1024],
            )
            ctx.free(h)
    finally:
        ctx.tini()


def test_put_accepts_raw_bytes(ctx, rng):
    """The put path takes bytes-likes (the C surface is void*-based; a
    Python caller reasonably hands in bytes) on every local kind, and a
    bytes-like ``local`` sizes the one-sided read."""
    for kind in (OcmKind.LOCAL_HOST, OcmKind.LOCAL_DEVICE):
        h = ctx.alloc(4096, kind)
        payload = bytes(rng.integers(0, 256, 4096, dtype=np.uint8))
        ctx.put(h, payload)
        np.testing.assert_array_equal(
            np.asarray(ctx.get(h)), np.frombuffer(payload, np.uint8)
        )
        ctx.put(h, bytearray(16), offset=100)
        assert not np.asarray(ctx.get(h, nbytes=16, offset=100)).any()
        out = ocm.ocm_copy_onesided(ctx, h, local=b"\0" * 16, op="read")
        assert np.asarray(out).shape == (16,)
        ctx.free(h)
