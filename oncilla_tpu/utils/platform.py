"""Platform plumbing for hostile/partial environments.

One concern today: dev images route the TPU through a tunnel plugin that
force-registers itself in every python process; when the tunnel is
wedged, jax initializes the plugin during backend discovery and hangs
``jax.devices()`` on EVERY platform — CPU-only code included. Paths that
never need the chip (test suites, multichip dryruns on virtual devices)
drop the plugin's backend factory before any device init.
"""

from __future__ import annotations


def force_cpu_devices(n_devices: int) -> None:
    """Force the CPU platform with ``n_devices`` virtual devices, robust
    to this image's quirks: a sitecustomize that pre-registers (and may
    pre-initialize) the TPU tunnel backend, and a wedged tunnel that
    would hang device discovery. Call as early as possible; safe to call
    after jax import.

    Used by the multichip dryrun gate and the examples; tests/conftest.py
    uses the env-var variant because it runs before jax is imported.
    """
    import os

    import jax

    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    drop_tunnel_plugin()
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except RuntimeError:
        # A backend is already initialized; drop it and re-apply — the
        # next jax.devices() re-initializes under the new config.
        import jax._src.xla_bridge as xb

        xb._clear_backends()
        jax.clear_caches()
        jax.config.update("jax_num_cpu_devices", n_devices)
    except Exception:  # older jax: XLA_FLAGS only works pre-init
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()

    devs = jax.devices()
    if devs[0].platform == "cpu" and len(devs) >= n_devices:
        return
    import jax._src.xla_bridge as xb

    xb._clear_backends()
    jax.clear_caches()
    devs = jax.devices()
    assert devs[0].platform == "cpu" and len(devs) >= n_devices, (
        f"could not provision {n_devices} virtual CPU devices; have {devs}"
    )


def honor_cpu_env() -> None:
    """Make an explicit ``JAX_PLATFORMS=cpu`` request stick. This image's
    sitecustomize force-sets jax_platforms to "axon,cpu" in every process,
    so the env var alone is silently overridden — and with a wedged
    tunnel, ANY device discovery then hangs. Entry points that users run
    with JAX_PLATFORMS=cpu (the daemon CLI, examples) call this before
    first device use; a no-op unless the env var says exactly "cpu"."""
    import os

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() != "cpu":
        return
    import jax

    jax.config.update("jax_platforms", "cpu")
    drop_tunnel_plugin()


def drop_tunnel_plugin(name: str = "axon") -> None:
    """Remove a PJRT plugin's backend factory so a wedged tunnel cannot
    hang device discovery. Only the tunnel-dialing plugin may be dropped
    — removing builtin platforms (e.g. 'tpu') breaks MLIR platform
    registration downstream. Call BEFORE the first ``jax.devices()``.

    Best effort by design: the registry is private jax API, and a layout
    change must degrade to the old (hang-prone) behavior, not an error.
    """
    try:
        import jax._src.xla_bridge as xb

        xb._backend_factories.pop(name, None)
    except Exception as e:  # noqa: BLE001 — registry layout changed
        from oncilla_tpu.utils.debug import printd

        printd("drop_tunnel_plugin: xla_bridge registry probe failed: %s", e)
