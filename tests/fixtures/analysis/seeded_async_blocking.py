"""Seeded violation: synchronous blocking calls inside coroutines.

Scanned explicitly by tests/test_asyncsafety.py — excluded from default
``python -m oncilla_tpu.analysis`` walks (lint.iter_py_files skips
``fixtures`` directories). Every construct here must fire
``async-blocking-call`` (or prove a documented non-finding).
"""

import asyncio
import socket
import time


async def sleep_on_loop():
    time.sleep(0.5)  # FINDING: freezes every task on this loop


async def dial_on_loop():
    socket.create_connection(("127.0.0.1", 1))  # FINDING: sync dial


async def wire_roundtrip_on_loop(sock, msg, request):
    request(sock, msg)  # FINDING: project blocking wire helper
    sock.recv(4096)     # FINDING: sync socket recv


async def sync_pool_on_loop(peer_pool, addr):
    with peer_pool.lease(addr):  # FINDING: sync PeerPool on the loop
        pass


async def file_on_loop(path):
    with open(path) as fh:  # FINDING: sync file I/O on the loop
        return fh.read()


async def ok_awaited():
    await asyncio.sleep(0.5)  # NOT a finding: the asyncio equivalent


async def ok_coroutine_wrapped(ch, msg):
    # NOT findings: .request here is a coroutine being constructed for a
    # wrapper, not a sync call executing inline.
    t = asyncio.get_running_loop().create_task(ch.request(msg))
    await asyncio.wait_for(ch.request(msg), timeout=1.0)
    return await t


async def ok_executor(loop, fn):
    return await loop.run_in_executor(None, fn)  # NOT a finding


def ok_sync_context(sock):
    sock.recv(1)  # NOT a finding: not a coroutine (lint's jurisdiction)


async def ok_suppressed():
    time.sleep(0.01)  # ocm-lint: allow[async-blocking-call]
