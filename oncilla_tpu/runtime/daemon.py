"""The per-host daemon: control-plane state machine + DCN data plane.

Python reference implementation of the daemon the reference builds as
``bin/oncillamem`` (/root/reference/src/main.c + mem.c): thread-per-connection
TCP server, rank-0 placement master, allocation registry, and — unlike the
reference, whose daemon never touches data — the server side of the DCN
data plane (REMOTE_HOST put/get into a daemon-owned host arena; the analogue
of the daemon-registered NIC buffer, alloc.c:171-176).

The C++ production daemon (runtime/native/) speaks the identical wire
protocol; this implementation is the executable spec and the test harness
(the in-process multi-daemon capability the reference lacked, SURVEY.md §4).

Protocol-race fix: the reference replies to DO_ALLOC *before* the server
listens for the data-plane connection ("XXX possible race condition",
/root/reference/src/mem.c:350-354). Here the owner reserves the extent and
registers the allocation before replying, and the data plane is
connectionless, so no such window exists.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time

from oncilla_tpu.analysis import alloctrace, waitwatch
from oncilla_tpu.analysis.lockwatch import make_lock, make_rlock
from oncilla_tpu.core.arena import ArenaAllocator, Extent, check_bounds
from oncilla_tpu.core.errors import (
    OcmAdmissionDenied,
    OcmBoundsError,
    OcmBusy,
    OcmConnectError,
    OcmDeadlineExceeded,
    OcmError,
    OcmInvalidHandle,
    OcmMoved,
    OcmOutOfMemory,
    OcmPlacementError,
    OcmNotPrimary,
    OcmProtocolError,
    OcmQuotaExceeded,
    OcmRemoteError,
    OcmReplicaUnavailable,
)
from oncilla_tpu import fabric as fabric_mod
from oncilla_tpu.control import hashring
from oncilla_tpu.control import leader as control_leader
from oncilla_tpu.core.hostmem import HostArena
from oncilla_tpu.core.kinds import OcmKind
from oncilla_tpu.elastic.rebalance import Rebalancer
from oncilla_tpu.runtime.membership import NodeEntry, as_view
from oncilla_tpu.runtime.pool import PeerPool
from oncilla_tpu.runtime.placement import (
    POLICIES,
    NodeResources,
    Placement,
)
from oncilla_tpu.obs import journal as obs_journal
from oncilla_tpu.obs import trace as obs_trace
from oncilla_tpu.qos.policy import (
    PRIO_HIGH,
    PRIO_LOW,
    PRIO_NORMAL,
    QosManager,
    suggest_backoff_ms,
    unpack_profile,
)
from oncilla_tpu.resilience.detector import (
    DeadVerdict,
    FailureDetector,
    PeerState,
    probe,
)
from oncilla_tpu.resilience.failover import FailoverCoordinator
from oncilla_tpu.resilience import timebudget
from oncilla_tpu.runtime.protocol import (
    FLAG_CAP_COALESCE,
    FLAG_CAP_DEADLINE,
    FLAG_CAP_FABRIC,
    FLAG_CAP_MUX,
    FLAG_CAP_QOS,
    FLAG_CAP_REPLICA,
    FLAG_CAP_TRACE,
    FLAG_DEADLINE,
    FLAG_FANOUT,
    FLAG_MORE,
    FLAG_HB_FWD,
    FLAG_MUX_TAG,
    FLAG_QOS_TAIL,
    FLAG_REPLICAS,
    FLAG_TRACE_CTX,
    VALID_FLAGS,
    WIRE_KIND,
    WIRE_KIND_INV,
    BufferedSock,
    ErrCode,
    Message,
    MsgType,
    RecvScratch,
    attach_tag,
    pack,
    pack_leader_tail,
    recv_msg,
    request,
    send_msg,
    split_tag,
)
from oncilla_tpu.runtime.protocol import (
    _data_len as _data_len_of,
    _sendall_vec as protocol_sendall_vec,
)
from oncilla_tpu.runtime.registry import AllocRegistry, RegEntry
from oncilla_tpu.utils.config import OcmConfig
from oncilla_tpu.utils.debug import Tracer, printd


# Bounded worker pool for out-of-order tagged control ops (mux serving).
# Control ops are short (or block on nested relay legs, which the pool
# must ride out) — size like the native daemon's data pool.
_MUX_POOL_WORKERS = min(8, max(2, os.cpu_count() or 2))

# Process-wide connection ids for the cancel/ack journal events: mux
# correlation tags are per-connection, so the audit invariant scopes
# them by (daemon track, conn, tag).
_conn_id_counter = 0
_conn_id_lock = make_lock("daemon._conn_id_lock")


def _next_conn_id() -> int:
    global _conn_id_counter
    with _conn_id_lock:
        _conn_id_counter += 1
        return _conn_id_counter


class _ConnMuxState:
    """Per-connection arrival bookkeeping for tagged control ops: which
    sequence numbers are still in flight, so a completion can tell
    whether it overtook an earlier arrival (the ``ooo`` counter — proof
    the out-of-order contract is actually exercised) — plus the
    server-side cancellation state: which tags are still open on the
    worker pool and which of those a CANCEL has revoked. ``cancel`` and
    ``finish_tag`` race under ONE lock, so exactly one of two outcomes
    holds per tag: the cancel wins (revoked=1 acked, the worker's reply
    suppressed — never an ack after a revoked cancel-ack, the audit
    invariant) or the completion wins (revoked=0, the ordinary reply
    stands and the client's orphan discard absorbs it)."""

    __slots__ = ("_lock", "_seq", "_inflight", "_open_tags", "_cancelled")

    def __init__(self) -> None:
        self._lock = make_lock("daemon._conn_mux_state")
        self._seq = 0
        self._inflight: set[int] = set()
        self._open_tags: set[int] = set()
        self._cancelled: set[int] = set()

    def note_start(self, tag: int | None = None) -> int:
        with self._lock:
            self._seq += 1
            self._inflight.add(self._seq)
            if tag is not None:
                self._open_tags.add(tag)
            return self._seq

    def note_done(self, seq: int) -> bool:
        """Retire ``seq``; True when an EARLIER arrival is still open
        (this completion is out of order)."""
        with self._lock:
            self._inflight.discard(seq)
            return any(s < seq for s in self._inflight)

    def cancel(self, tag: int) -> bool:
        """Revoke ``tag`` if it is still open on the pool; True = the
        revocation binds (the worker's reply WILL be suppressed), False
        = nothing to revoke (unknown tag, already answered, or an
        inline data leg past the point of no return)."""
        with self._lock:
            if tag in self._open_tags and tag not in self._cancelled:
                self._cancelled.add(tag)
                return True
            return False

    def take_if_cancelled(self, tag: int) -> bool:
        """Pre-dispatch check: True when a binding cancel already
        revoked ``tag`` (the tag state is consumed — the op must not
        run, and no reply may be sent)."""
        with self._lock:
            if tag in self._cancelled:
                self._cancelled.discard(tag)
                self._open_tags.discard(tag)
                return True
            return False

    def finish_tag(self, tag: int) -> bool:
        """Retire ``tag`` at completion; True = send the reply, False =
        a binding cancel got there first (suppress it)."""
        with self._lock:
            self._open_tags.discard(tag)
            if tag in self._cancelled:
                self._cancelled.discard(tag)
                return False
            return True


class Daemon:
    """One per host. ``rank == 0`` is the placement master."""

    def __init__(
        self,
        rank: int,
        entries: list[NodeEntry],
        config: OcmConfig | None = None,
        policy: str = "capacity",
        ndevices: int = 1,
        host: str | None = None,
        snapshot_path: str | None = None,
        incarnation: int | None = None,
        listener: socket.socket | None = None,
    ):
        self.snapshot_path = snapshot_path
        self.rank = rank
        # Membership is a LIVE epoch-stamped table (elastic/): a plain
        # nodefile list is wrapped, an existing ClusterView is shared
        # as-is (the LocalCluster idiom — every in-process daemon sees
        # one table, exactly like the reference's global nodefile, but
        # mutable under the JOIN/LEAVE protocol).
        self.entries = as_view(entries)
        self.config = config or OcmConfig()
        self.ndevices = ndevices
        # The control/data plane is unauthenticated (like the reference's,
        # sock.c binds INADDR_ANY) — so default to loopback; exposing it on
        # other interfaces is an explicit opt-in via the host= argument
        # (typically the nodefile hostname) or OCM_BIND_HOST=0.0.0.0.
        if host is None:
            host = os.environ.get("OCM_BIND_HOST", "127.0.0.1")
        self.host = host
        self.port = entries[rank].port
        # One-sided fabrics this daemon serves (fabric/): with
        # OCM_FABRIC=shm/auto the host arena is BACKED by a named
        # shared-memory segment, advertised at CONNECT behind
        # FLAG_CAP_FABRIC so same-host clients put/get by memcpy. A
        # failed registration (tiny /dev/shm) degrades to tcp-only.
        self.fabrics = fabric_mod.server_fabrics(self.config)
        backing = (
            self.fabrics["shm"].buffer() if "shm" in self.fabrics else None
        )
        # Counters for the per-fabric transfer metrics (STATUS tail +
        # ocm_fabric_* prom families): CONNECT negotiations by outcome
        # and served one-sided ops/bytes. Plain int bumps under the GIL,
        # same discipline as res_counters.
        self.fabric_counters = {
            "selected_shm": 0,   # CONNECT offers granted with a descriptor
            "selected_tcp": 0,   # offers declined (nothing to advertise)
            "shm_puts": 0,
            "shm_gets": 0,
            "shm_put_bytes": 0,
            "shm_get_bytes": 0,
        }
        # Daemon-owned storage for the REMOTE_HOST arm (DCN fabric).
        self.host_arena = HostArena(
            self.config.host_arena_bytes, self.config.alignment,
            backing=backing,
        )
        # Bookkeeping-only allocators for this host's device arenas: the HBM
        # bytes live in the SPMD app processes (the ICI fabric); the daemon
        # hands out extents inside them.
        self.device_books = [
            ArenaAllocator(self.config.device_arena_bytes, self.config.alignment)
            for _ in range(ndevices)
        ]
        self.registry = AllocRegistry(
            rank, self.config.lease_s,
            app_stale_leases=self.config.app_stale_leases,
        )
        self.policy = POLICIES[policy]()
        self.peers = PeerPool()
        # Multi-tenant QoS (qos/): tenant profiles + admission accounting
        # for apps whose ORIGIN daemon this is; rank 0 additionally runs
        # the back-pressure check and, with policy="loadaware", feeds the
        # placement policy from peer STATUS polls in the reaper loop.
        self.qos = QosManager(self.config)
        self._last_load_poll = time.monotonic()
        # FROZEN tier (persist/): disk-backed extent store, one
        # directory per daemon rank. Constructed ONLY when configured
        # (OCM_FROZEN_DIR set and OCM_FROZEN!=0) — None keeps every
        # demotion/eviction/data path byte-identical to the pre-persist
        # daemon. The open itself adopts nothing; surviving extents are
        # re-registered by _adopt_frozen() in start(). A failed open
        # (unwritable dir) degrades to no-FROZEN rather than killing
        # the daemon.
        self._frozen = None
        # Reentrant: a thaw's arena-full retry runs the pressure
        # evictor, whose demote leg re-enters the same lock.
        self._frz_lock = make_rlock("daemon._frz_lock")
        self.frz_counters = {
            "demotes": 0,        # victims spilled to disk (tier_demote)
            "promotes": 0,       # frozen entries thawed back into the arena
            "lost": 0,           # corrupt/torn entries refused at open/read
            "warm_boot_extents": 0,  # extents re-adopted after a restart
        }
        if self.config.frozen_enabled:
            from oncilla_tpu.persist.store import FrozenStore

            try:
                self._frozen = FrozenStore(
                    os.path.join(self.config.frozen_dir, f"r{self.rank}"),
                    max_bytes=self.config.frozen_max_bytes,
                )
                self.frz_counters["lost"] = len(self._frozen.lost)
            except OSError as e:
                printd("daemon r%d: frozen store open failed: %s",
                       self.rank, e)
        # Device-plane endpoint (host, port) registered by the SPMD
        # controller's client via PLANE_SERVE; device-kind data ops are
        # relayed there (tuple rebind is atomic under the GIL). The daemon
        # that takes a fresh registration pushes it to every peer; ranks
        # still pending live in _plane_unsynced and are retried by the
        # reaper loop.
        self.plane_addr: tuple[str, int] | None = None
        self._plane_unsynced: set[int] = set()
        self._plane_sync_lock = make_lock("daemon._plane_sync_lock")
        # True once this daemon has relayed a device-kind write: from then
        # on freed device extents MUST be scrubbed through the plane even
        # if the local endpoint is momentarily unknown (master hop).
        self._device_writes_relayed = False
        self._listener: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._running = threading.Event()
        self._started_ok = False
        self._conns: set[socket.socket] = set()
        self._conns_mu = make_lock("daemon._conns_mu")
        # OCM_ALLOCTRACE ledger scope for registry entries this daemon
        # owns (id-qualified: one process hosts many daemons in tests).
        self._trace_scope = f"daemon:r{self.rank}:{id(self):#x}"
        # Served data-plane telemetry: per-op stats plus the per-transfer
        # ring (bytes/Gbps of each coalesced burst), surfaced as the JSON
        # data tail of STATUS_OK — trailing data on a reply is invisible
        # to old clients, so the schema stays v2-compatible. The track
        # label keys this daemon's timeline in exported traces (one test
        # process hosts many daemons; pid alone cannot tell them apart).
        self.tracer = Tracer(track=f"daemon-r{self.rank}")
        # Trace-capability bits per peer address, probed lazily with a
        # CONNECT on the first forwarded hop that has a context to carry
        # (the client-side _dcn_caps precedent) — a capability is a
        # property of the peer's software, not of one connection, so one
        # probe covers every pooled socket to that address.
        self._peer_caps: dict[tuple[str, int], int] = {}
        self._peer_caps_lock = make_lock("daemon._peer_caps_lock")
        # Per-serve-thread reusable DATA_GET_OK snapshot buffer: a fresh
        # bytes() per 16 MiB chunk costs an allocation + page faults each
        # time (measured ~4x the warm-copy cost); each connection has its
        # own serve thread, so thread-local reuse needs no locking.
        self._get_buf = threading.local()
        # -- resilience (resilience/) -----------------------------------
        # Cluster epoch: bumped by rank 0 on every DEAD verdict, gossiped
        # on PING and adopted max-wins everywhere; a fenced daemon (one
        # that outlived its own DEAD verdict) refuses writes with
        # STALE_EPOCH so it can never serve split-brain traffic. The
        # incarnation is this daemon OBJECT's identity: a restarted
        # daemon on the same port has a fresh one, so a stale fencing
        # broadcast can never hit the replacement.
        self.epoch = 0
        self._epoch_lock = make_lock("daemon._epoch_lock")
        self._fenced = False
        self.incarnation = (
            incarnation or int.from_bytes(os.urandom(8), "little") or 1
        )
        # Pre-bound listener (elastic/join_cluster): the joiner binds
        # and LISTENS before REQ_JOIN so peers reaching for the new rank
        # queue in the backlog instead of bouncing off a closed port.
        self._prebound = listener
        # -- elastic membership (elastic/) -------------------------------
        # Forwarding tombstones for live-migrated allocations:
        # alloc_id -> (new owner rank, origin_pid, origin_rank, stamp).
        # Data ops on a tombstoned id answer typed MOVED (the client
        # repoints its handle); DO_FREE forwards; heartbeats from the
        # owning app are forwarded so the migrated copy's lease stays
        # renewed until the client repoints. Pruned by the reaper once
        # the app goes stale.
        self._moved: dict[int, tuple[int, int, int, float]] = {}
        self._moved_lock = make_lock("daemon._moved_lock")
        # In-flight outbound migrations (source side): alloc_id ->
        # {"dirty": [(offset, nbytes)...], "fence": bool}. Client puts
        # landing mid-stream are recorded for the pre-copy dirty passes;
        # once fenced, they answer retryable NOT_PRIMARY and the ladder
        # re-lands them on the target after the flip.
        self._migrations: dict[int, dict] = {}
        self._mig_lock = make_lock("daemon._mig_lock")
        # MEMBER_UPDATE broadcast retry set (rank 0): peers that have
        # not confirmed the current member table yet; the reaper loop
        # re-pushes until every live member converges (the
        # _plane_unsynced pattern).
        self._member_unsynced: set[int] = set()
        self._member_sync_lock = make_lock("daemon._member_sync_lock")
        self.ela_counters = {
            "joins": 0,                  # rank 0: REQ_JOIN admissions
            "leaves": 0,                 # rank 0: graceful departures
            "migrations_started": 0,     # source side
            "migrations_completed": 0,
            "migrations_aborted": 0,
            "migration_bytes": 0,        # bytes whose ownership flipped
        }
        self.res_counters = {
            "deaths": 0,           # DEAD verdicts issued (leader only)
            "promotions": 0,       # replica entries promoted to primary here
            "rereplications": 0,   # repair copies driven (leader only)
            "repl_put_errors": 0,  # put fan-out legs that failed
            "repl_put_skips": 0,   # fan-out legs skipped (replica DEAD)
        }
        # -- decentralized control plane (control/) ----------------------
        # The master role is a dynamic LEADERSHIP, not rank 0's identity:
        # every master-bound leg (ADD_NODE, REQ_ALLOC proxy, NOTE_*,
        # SUSPECT reports, plane master hop, JOIN/LEAVE) targets
        # entries[leader_rank]. Boot-time leader is rank 0 — with
        # OCM_STANDBY_MASTERS unset it never moves, and none of the
        # MASTER_STATE/LEADER_* family ever rides the wire.
        self.leader_rank = 0
        self.leader_epoch = 0
        self._elect_lock = make_lock("daemon._elect_lock")
        self.ldr_counters = {
            "elections_won": 0,       # this daemon took leadership
            "elections_observed": 0,  # leadership changed under us
            "handoffs": 0,            # voluntary transfers (either end)
            "placements": 0,          # REQ_ALLOCs placed HERE as leader
            "hash_placements": 0,     # REQ_ALLOCs hash-placed locally
            "state_pushes": 0,        # MASTER_STATE pushes sent (leader)
            "state_resyncs": 0,       # whole-resyncs at promotion
        }
        # Replicated master state held AS a standby: the raw CRC-framed
        # document exactly as pushed (validated before storing AND again
        # at promotion — a copy torn on disk/in memory is refused whole).
        self._master_state_raw: bytes | None = None
        self._master_state_ts = 0.0
        self._master_state_seq = 0
        self._state_seq = 0          # leader-side push sequence
        self._state_lock = make_lock("daemon._state_lock")
        # LEADER_UPDATE broadcast retry set + the fields to re-send
        # (the _member_unsynced pattern: reaper retries stragglers).
        self._leader_unsynced: set[int] = set()
        self._leader_update_fields: dict | None = None
        self._leader_sync_lock = make_lock("daemon._leader_sync_lock")
        # Hash placement's deferred accounting: NOTE_ALLOC messages bound
        # for the leader, drained by the reaper so the alloc path itself
        # makes ZERO leader round trips (the acceptance pin).
        self._acct_pending: list[Message] = []
        self._acct_lock = make_lock("daemon._acct_lock")
        # Harness-level partition emulation (resilience/chaos "isolate"):
        # inbound connections are dropped, outbound pool leases refused,
        # probes short-circuit to failures — a fully partitioned host.
        self._partitioned = False
        # Mux serving (runtime/mux.py): tagged control ops complete OUT
        # OF ORDER on a small shared worker pool (created lazily — a
        # daemon that never sees a mux client never pays the threads);
        # per-connection write locks keep reply frames whole. Counters
        # feed STATUS/prom and the obs table's in-flight column.
        self._mux_pool = None
        self._mux_pool_lock = make_lock("daemon._mux_pool_lock")
        self._mux_counters = {
            "conns": 0,          # connections that negotiated mux
            "tagged_ops": 0,     # tagged requests served
            "inflight": 0,       # tagged control ops in the pool NOW
            "peak_inflight": 0,
            "ooo": 0,            # replies sent out of arrival order
        }
        self._mux_ctr_lock = make_lock("daemon._mux_ctr_lock")
        # Time-bounded data plane (resilience/timebudget.py): budget and
        # cancellation accounting. Plain int bumps under the GIL (the
        # res_counters discipline); last_budget_ms is the most recent
        # FLAG_DEADLINE tail received — what the cross-hop decrement
        # test reads to prove a relayed budget arrived strictly smaller.
        self.tb_counters = {
            "deadline_exceeded": 0,  # expired work refused typed
            "cancels": 0,            # CANCEL requests served
            "cancels_revoked": 0,    # ... that actually revoked an op
            "cancel_drops": 0,       # replies suppressed post-cancel
            "cancel_frees": 0,       # completed-then-cancelled allocs
            #                          unwound through the free path
            "last_budget_ms": -1,
        }
        # Testability hook (bench/tests, never config): artificial serve
        # delay for the named message types — how a "slow replica" is
        # built for the hedged-read cells and how a cancel storm gets a
        # deterministic window to land in.
        self.serve_delay_s = 0.0
        self.serve_delay_types: frozenset = frozenset()
        # Sibling hook, different placement: serve_delay sleeps BEFORE
        # the serve-side tracer span (a slow wire/replica — invisible in
        # ocm_op_latency_seconds), handler_delay sleeps INSIDE _dispatch
        # (a slow handler — the latency histograms see it). The SLO
        # selftest's seeded-burn fixture is built on the latter.
        self.handler_delay_s = 0.0
        self.handler_delay_types: frozenset = frozenset()
        self.detector = (
            FailureDetector(
                len(entries), rank,
                suspect_after=self.config.suspect_after,
                dead_after=self.config.dead_after,
            )
            if self.config.detect and len(entries) > 1 else None
        )
        # Every daemon carries the coordination machinery (cheap, inert
        # objects); only the CURRENT leader drives it — a promoted
        # standby resumes failover/rebalance without construction races.
        self._failover = FailoverCoordinator(self)
        self._rebalancer = Rebalancer(self)
        self._last_probe = time.monotonic()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._prebound is not None:
            # elastic join: the socket was bound AND listening before
            # REQ_JOIN, so peers dialing the freshly announced rank
            # queue in the backlog until the accept loop drains them.
            self._listener, self._prebound = self._prebound, None
        else:
            self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._listener.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
            )
            # Loopback by default (see __init__); multi-host deployments
            # pass the nodefile hostname or opt into the wildcard
            # explicitly. Peers dial the nodefile's addr column, which
            # need not match what the local resolver maps our own
            # hostname to.
            self._listener.bind((self.host, self.port))
            self._listener.listen(64)
        if self.port == 0:  # ephemeral port (tests)
            self.port = self._listener.getsockname()[1]
            self.entries[self.rank] = NodeEntry(
                self.rank, self.host, self.port, self.entries[self.rank].addr
            )
        self._running.set()
        # Join the cluster (ADD_NODE resets rank-0 accounting for this node)
        # and restore the snapshot (NOTE_ALLOC resyncs it) BEFORE serving:
        # the listen backlog queues early connections, so no request can
        # claim an extent the snapshot needs (the C++ daemon orders the same
        # way, native/daemon.cc restore-before-accept).
        if self.rank == self.leader_rank:
            self.policy.add_node(self._own_resources())
        else:
            self._notify_leader()
        self._maybe_restore()
        # Warm boot: re-adopt frozen extents that survived a hard kill
        # (no snapshot was written) AFTER the snapshot restore, so
        # snapshot-known entries win and only orphans are adopted.
        self._adopt_frozen()
        t = threading.Thread(target=self._accept_loop, daemon=True, name=f"d{self.rank}-accept")
        t.start()
        self._threads.append(t)
        r = threading.Thread(target=self._reaper_loop, daemon=True, name=f"d{self.rank}-reaper")
        r.start()
        self._threads.append(r)
        self._started_ok = True
        printd("daemon rank=%d listening on %s:%d", self.rank, self.host, self.port)

    def stop(self) -> None:
        # Quiesce first: stop accepting, kick every serve thread off its
        # socket, and only then snapshot — otherwise in-flight requests can
        # tear the snapshot (half-written puts, allocations granted after
        # the registry walk).
        self._running.clear()
        if self._listener is not None:
            # shutdown() wakes the thread blocked in accept(); a bare close()
            # leaves the kernel file description (and the LISTEN socket)
            # alive until that accept returns, blocking port rebinds.
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conns_mu:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            with self._conns_mu:
                if not self._conns:
                    break
            time.sleep(0.01)
        # Snapshot only if this daemon actually served (a failed start must
        # not clobber a good on-disk snapshot with an empty registry).
        if self.snapshot_path and self._started_ok:
            try:
                self.save_snapshot()
            except OSError:
                printd("daemon %d: snapshot write failed", self.rank)
        self.peers.close()
        with self._mux_pool_lock:
            pool, self._mux_pool = self._mux_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        # Unregister fabrics LAST: the snapshot above reads the arena,
        # which an shm fabric backs. Idempotent (kill() may have run).
        for f in self.fabrics.values():
            f.teardown()

    def kill(self) -> None:
        """Hard-kill (resilience/chaos.py): the crash the failover
        machinery exists for. No snapshot, no drain, no courtesy to
        in-flight requests — every socket is torn down NOW, exactly what
        a SIGKILL'd daemon process looks like to its peers. Idempotent;
        a later :meth:`stop` (cluster teardown) is a no-op on top."""
        self._started_ok = False  # a kill must never write a snapshot
        # Black-box flush FIRST: the journal ring is the evidence the
        # post-mortem auditor needs, and a hard kill used to discard it.
        # With the flight recorder armed (OCM_FLIGHTREC) the ring is
        # dumped to a labelled segment; streamed duplicates dedup away
        # at merge time, so this can only ADD evidence.
        obs_journal.record(
            "daemon_kill", track=self.tracer.track, rank=self.rank,
        )
        obs_journal.spill_ring(label=f"kill-r{self.rank}")
        self._running.clear()
        if self._listener is not None:
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conns_mu:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self.peers.close()
        with self._mux_pool_lock:
            pool, self._mux_pool = self._mux_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        # A killed daemon must not leak its segment name in /dev/shm:
        # unlink NOW (attached peers' mappings stay valid; only the name
        # dies — exactly a SIGKILL'd process whose parent reaps the
        # segment). The chaos-harness kill path asserts this.
        for f in self.fabrics.values():
            f.teardown()

    # -- epoch / fencing (resilience/) -----------------------------------

    def bump_epoch(self) -> int:
        """Leader only: advance the cluster epoch (DEAD verdicts,
        membership changes, leadership transfer)."""
        with self._epoch_lock:
            self.epoch += 1
            return self.epoch

    def _adopt_epoch(self, epoch: int) -> None:
        """Max-wins epoch gossip (PING and every resilience message)."""
        with self._epoch_lock:
            if epoch > self.epoch:
                self.epoch = epoch

    def _fence(self, epoch: int) -> None:
        if not self._fenced:
            self._fenced = True
            obs_journal.record(
                "fenced", track=self.tracer.track,
                rank=self.rank, epoch=epoch,
            )
            printd("daemon %d FENCED at epoch %d: refusing writes",
                   self.rank, epoch)

    # -- leadership (control/): the master role as an epoch-fenced lease -

    @property
    def is_leader(self) -> bool:
        """Whether THIS daemon currently coordinates the cluster. A
        fenced daemon is never the leader, whatever it believes — its
        verdicts were superseded by a newer epoch."""
        return self.rank == self.leader_rank and not self._fenced

    def _leader_entry(self) -> NodeEntry:
        r = self.leader_rank
        if 0 <= r < len(self.entries):
            return self.entries[r]
        return self.entries[0]

    def _not_master_err(self, what: str) -> Message:
        """Typed NOT_MASTER rejection. Once leadership is dynamic the
        tail names the current leader (rank + address) so the sender
        re-aims instead of spinning — the MOVED redirect pattern applied
        to the master role. Static clusters keep the PR-11 tail-less
        frame (wire byte-identity when the feature is unset)."""
        tail = b""
        if self.config.standby_masters > 0 or self.leader_rank != 0:
            le = self._leader_entry()
            tail = pack_leader_tail(
                self.leader_rank, le.connect_host, le.port
            )
        return _err(
            ErrCode.NOT_MASTER, f"{what} sent to non-master", tail
        )

    def _adopt_leader_hint(self, err) -> None:
        """A peer's NOT_MASTER redirect named the current leader."""
        lr = getattr(err, "leader_rank", None)
        if lr is not None and 0 <= lr < len(self.entries):
            if lr != self.leader_rank:
                printd("daemon %d: leader hint %d -> %d",
                       self.rank, self.leader_rank, lr)
            self.leader_rank = lr

    def set_partitioned(self, on: bool) -> None:
        """Harness seam (resilience/chaos "isolate"): emulate a full
        network partition of this daemon's host. Inbound requests are
        dropped mid-frame (peers and probes see a torn connection),
        outbound pool leases refuse, and the detector tick records
        probe failures without dialing — deterministic, reversible, and
        honest about what a partitioned process can still do: keep its
        own state and keep believing it leads."""
        self._partitioned = bool(on)
        self.peers.set_blocked(on)
        obs_journal.record(
            "chaos_isolate" if on else "chaos_heal_isolate",
            track=self.tracer.track, rank=self.rank,
        )

    def _standby_ranks(self) -> list[int]:
        """The k lowest-rank live members after the leader — where the
        master state replicates. Deterministic from the shared view, so
        every rank agrees who the standbys are."""
        k = self.config.standby_masters
        if k <= 0:
            return []
        out = [
            e.rank for e in self.entries
            if e.rank != self.rank
            and e.port
            and not self.entries.has_left(e.rank)
            and not self._believed_dead(e.rank)
        ]
        return sorted(out)[:k]

    def _push_master_state(self) -> None:
        """Leader, reaper-tick cadence: replicate the coordination state
        to every standby under the snapshot+CRC discipline. Small (a few
        KiB), so a full copy per tick beats delta bookkeeping; the seq
        lets standbys drop stale reordered pushes."""
        with self._state_lock:
            self._state_seq += 1
            seq = self._state_seq
        doc = control_leader.build_state(self, seq)
        raw = control_leader.pack_state(doc)
        msg_fields = {"seq": seq, "epoch": self.epoch, "leader": self.rank}
        for r in self._standby_ranks():
            e = self.entries[r]
            try:
                self.peers.request(
                    e.connect_host, e.port,
                    Message(MsgType.MASTER_STATE, dict(msg_fields), raw),
                )
                self.ldr_counters["state_pushes"] += 1
            except (OSError, OcmError):
                pass  # next tick retries; the standby resyncs whole if
                # it must lead from a stale copy

    def _on_master_state(self, msg: Message) -> Message:
        """Standby side: store the leader's pushed state. The CRC is
        verified BEFORE the copy is stored (a torn push is refused with
        a typed error, and the leader re-pushes next tick) and verified
        AGAIN at promotion — the copy may rot in between."""
        f = msg.fields
        self._adopt_epoch(f["epoch"])
        if 0 <= f["leader"] < len(self.entries):
            self.leader_rank = f["leader"]
        control_leader.unpack_state(msg.data)  # raises on any corruption
        with self._state_lock:
            if f["seq"] >= self._master_state_seq:
                self._master_state_raw = bytes(msg.data)
                self._master_state_seq = f["seq"]
                self._master_state_ts = time.monotonic()
        return Message(MsgType.MASTER_STATE_OK, {"seq": f["seq"]})

    def _adopt_master_state(self) -> bool:
        """Promotion path: lead from the replicated copy if — and only
        if — it verifies AND is fresh within the leader lease. Returns
        False when the winner must re-sync whole instead."""
        with self._state_lock:
            raw, ts = self._master_state_raw, self._master_state_ts
        if raw is None:
            return False
        age = time.monotonic() - ts
        horizon = max(self.config.leader_lease_s,
                      3 * self.config.heartbeat_s)
        if age > horizon:
            printd("daemon %d: replicated master state is %.2fs old "
                   "(lease %.2fs) — resyncing whole", self.rank, age,
                   horizon)
            return False
        try:
            doc = control_leader.unpack_state(raw)
        except OcmProtocolError as e:
            obs_journal.record(
                "master_state_corrupt", track=self.tracer.track,
                rank=self.rank, error=str(e),
            )
            printd("daemon %d: replicated master state REFUSED: %s",
                   self.rank, e)
            return False
        control_leader.apply_state(self, doc)
        return True

    def _rebuild_master_state(self) -> None:
        """Whole re-sync: reconstruct the placement accounting from the
        survivors' own numbers (STATUS carries capacities + live bytes)
        instead of trusting a torn or stale replica. Unreachable peers
        are skipped — the detector resolves them, and NOTE_* traffic
        self-corrects the books as it always has."""
        self.ldr_counters["state_resyncs"] += 1
        obs_journal.record(
            "leader_resync", track=self.tracer.track,
            rank=self.rank, epoch=self.epoch,
        )
        rows = [{
            "rank": self.rank,
            "ndevices": self.ndevices,
            "device_arena_bytes": self.config.device_arena_bytes,
            "host_arena_bytes": self.config.host_arena_bytes,
            "device_used": [b.bytes_live for b in self.device_books],
            "host_used": self.host_arena.allocator.bytes_live,
        }]
        for e in self.entries:
            if e.rank == self.rank or not e.port:
                continue
            if self.entries.has_left(e.rank) or self._believed_dead(e.rank):
                continue
            try:
                r = self.peers.request(
                    e.connect_host, e.port, Message(MsgType.STATUS, {})
                )
            except (OSError, OcmError):
                continue
            caps = {}
            if r.data:
                import json

                try:
                    caps = json.loads(bytes(r.data)).get("caps") or {}
                except (ValueError, UnicodeDecodeError):
                    caps = {}
            rows.append({
                "rank": e.rank,
                "ndevices": caps.get("ndevices", 1),
                "device_arena_bytes": caps.get(
                    "device_arena_bytes", self.config.device_arena_bytes
                ),
                "host_arena_bytes": caps.get(
                    "host_arena_bytes", self.config.host_arena_bytes
                ),
                # The total is accurate; the per-device split is not
                # reported — park it on device 0 (device placement is
                # capacity-gated per device, so this only errs safe).
                "device_used": [r.fields.get("device_bytes_live", 0)],
                "host_used": r.fields.get("host_bytes_live", 0),
            })
        dead = self.detector.dead_ranks() if self.detector else set()
        self.policy.restore(rows, dead)

    def _maybe_elect(self) -> None:
        """Standby election check (reaper tick, leader believed dead):
        the lowest live rank takes over. Everyone computes the same rule
        from their own view; non-winners keep probing the smaller ranks
        so a dead would-be winner is discovered and the rule re-runs."""
        det = self.detector
        dead = det.dead_ranks() if det is not None else set()
        winner = control_leader.elect(self.entries, dead, self.rank)
        if winner == self.rank:
            self._become_leader()

    def _become_leader(self) -> None:
        """Take the master role after the leader's DEAD verdict: adopt
        (or rebuild) the replicated state, bump + fence under a new
        epoch, broadcast LEADER_UPDATE, then resume the dead leader's
        coordination — failover, promotion, re-replication — exactly
        where it stopped."""
        with self._elect_lock:
            if self.is_leader or self._fenced:
                return
            old = self.leader_rank
            if not self._believed_dead(old):
                return
            old_inc = (
                self.detector.incarnation(old) if self.detector else 0
            )
            resync = not self._adopt_master_state()
            if resync:
                # Deliberately dialed under _elect_lock: the adoption
                # check, whole-cluster resync, and epoch bump must be
                # atomic w.r.t. the handoff/update handlers or a
                # concurrent LEADER_HANDOFF could interleave half-built
                # master state. The cross-process hazard stays open-
                # ended only in theory: the resync legs are STATUS
                # (leaf handlers — no back-dial), so the reverse
                # rpc:daemon -> _elect_lock edge cannot complete a
                # cycle through them; OCM_WAITWATCH=1 watches the
                # dynamic graph for regressions.
                self._rebuild_master_state()  # ocm-lint: allow[lock-across-rpc]
            self.leader_rank = self.rank
            epoch = self.bump_epoch()
            self.leader_epoch = epoch
            self.ldr_counters["elections_won"] += 1
        self.policy.mark_dead(old)
        if self.detector is not None:
            self.detector.mark_dead(old)
        obs_journal.record(
            "leader_elect", track=self.tracer.track,
            rank=self.rank, prev=old, epoch=epoch, resync=resync,
        )
        obs_journal.record(
            "leader_fence", track=self.tracer.track,
            rank=old, epoch=epoch,
        )
        printd("daemon %d: ELECTED leader at epoch %d (rank %d fenced%s)",
               self.rank, epoch, old, ", state resynced" if resync else "")
        if 0 <= old < len(self.entries):
            de = self.entries[old]
            self.peers.evict(de.connect_host, de.port)
        self._queue_leader_sync(dead_rank=old, inc=old_inc)
        # Resume coordination: the deposed leader's allocations fail
        # over under this leadership (promote + re-replicate), through
        # the same coordinator a rank-0 master always ran.
        try:
            self._failover.node_dead(old)
        except Exception as e:  # noqa: BLE001 — leadership must survive
            # a partially unreachable cluster; repair retries via the
            # detector's ongoing verdicts
            printd("daemon %d: post-election failover for rank %d "
                   "failed: %s", self.rank, old, e)

    def handoff_leadership(self) -> int:
        """Voluntary transfer (the clean-LEAVE path rank 0 never had):
        push the final state synchronously inside the handoff frame —
        the successor refuses a CRC-failing copy, and then this daemon
        simply remains leader — and demote only once the successor
        confirmed. Returns the new leader's rank."""
        if not self.is_leader:
            raise OcmError(f"rank {self.rank} is not the leader")
        det_dead = self.detector.dead_ranks() if self.detector else set()
        succ = min(
            (e.rank for e in self.entries
             if e.rank != self.rank and e.port
             and e.rank not in det_dead
             and not self.entries.has_left(e.rank)),
            default=None,
        )
        if succ is None:
            raise OcmError("no live member to hand leadership to")
        with self._elect_lock:
            epoch = self.bump_epoch()
            with self._state_lock:
                self._state_seq += 1
                seq = self._state_seq
            doc = control_leader.build_state(self, seq, leader=succ)
            doc["epoch"] = epoch
            raw = control_leader.pack_state(doc)
        se = self.entries[succ]
        self.peers.request(
            se.connect_host, se.port,
            Message(
                MsgType.LEADER_HANDOFF,
                {"leader": succ, "epoch": epoch,
                 "from_rank": self.rank, "inc": self.incarnation},
                raw,
            ),
        )
        self.leader_rank = succ
        self.leader_epoch = epoch
        self.ldr_counters["handoffs"] += 1
        obs_journal.record(
            "leader_handoff", track=self.tracer.track,
            src=self.rank, target=succ, epoch=epoch,
        )
        printd("daemon %d: leadership handed off to rank %d (epoch %d)",
               self.rank, succ, epoch)
        return succ

    def _on_leader_handoff(self, msg: Message) -> Message:
        """Successor side of a voluntary transfer: verify + adopt the
        final state (a torn tail REFUSES the handoff — the old leader
        keeps leading), then announce."""
        f = msg.fields
        if f["leader"] != self.rank:
            raise OcmInvalidHandle(
                f"handoff names rank {f['leader']}, this is {self.rank}"
            )
        doc = control_leader.unpack_state(msg.data)  # raises on corruption
        control_leader.apply_state(self, doc)
        self._adopt_epoch(f["epoch"])
        with self._elect_lock:
            self.leader_rank = self.rank
            self.leader_epoch = f["epoch"]
            self.ldr_counters["handoffs"] += 1
        obs_journal.record(
            "leader_handoff", track=self.tracer.track,
            src=f["from_rank"], target=self.rank, epoch=f["epoch"],
        )
        printd("daemon %d: leadership ADOPTED from rank %d (epoch %d)",
               self.rank, f["from_rank"], f["epoch"])
        self._queue_leader_sync(dead_rank=-1, inc=0)
        return Message(MsgType.LEADER_OK, {"epoch": self.epoch})

    def _queue_leader_sync(self, dead_rank: int, inc: int) -> None:
        """(Re)arm the LEADER_UPDATE broadcast toward every live member
        and push once inline; the reaper retries stragglers (the
        _member_unsynced pattern)."""
        with self._leader_sync_lock:
            self._leader_update_fields = {
                "leader": self.leader_rank,
                "epoch": self.epoch,
                "dead_rank": dead_rank,
                "inc": inc,
            }
            self._leader_unsynced = {
                e.rank for e in self.entries
                if e.rank != self.rank and e.port
                and not self.entries.has_left(e.rank)
            }
        self._sync_leader_update()

    def _sync_leader_update(self) -> None:
        with self._leader_sync_lock:
            fields = self._leader_update_fields
            pending = sorted(self._leader_unsynced)
        if fields is None:
            return
        dead_rank = fields["dead_rank"]
        for r in pending:
            if self.entries.has_left(r):
                with self._leader_sync_lock:
                    self._leader_unsynced.discard(r)
                continue
            # The deposed leader gets the broadcast best-effort exactly
            # once (it fences itself on receipt, or later via the PING
            # STALE_EPOCH sentinel); other dead ranks are skipped.
            if r != dead_rank and self._believed_dead(r):
                with self._leader_sync_lock:
                    self._leader_unsynced.discard(r)
                continue
            e = self.entries[r]
            try:
                self.peers.request(
                    e.connect_host, e.port,
                    Message(MsgType.LEADER_UPDATE, dict(fields)),
                )
                with self._leader_sync_lock:
                    self._leader_unsynced.discard(r)
            except (OSError, OcmError):
                if r == dead_rank:
                    # One best-effort attempt only — a genuinely dead
                    # leader would pin the retry set forever.
                    with self._leader_sync_lock:
                        self._leader_unsynced.discard(r)

    def _on_leader_update(self, msg: Message) -> Message:
        """Adopt an election/handoff broadcast. The deposed leader —
        matched by (rank, incarnation), exactly the PR-5 owner-fencing
        discipline — fences itself; everyone else re-aims master-bound
        traffic at the new leader and EAGERLY drops pooled connections
        to the dead one (the detector's evict discipline)."""
        f = msg.fields
        self._adopt_epoch(f["epoch"])
        dr = f["dead_rank"]
        if dr == self.rank:
            if f["inc"] in (0, self.incarnation):
                self._fence(f["epoch"])
                return Message(MsgType.LEADER_OK, {"epoch": self.epoch})
        lr = f["leader"]
        if 0 <= lr < len(self.entries):
            prev = self.leader_rank
            self.leader_rank = lr
            self.leader_epoch = max(self.leader_epoch, f["epoch"])
            if prev != lr and lr != self.rank:
                self.ldr_counters["elections_observed"] += 1
        if dr >= 0 and dr != self.rank and dr < len(self.entries):
            if self.detector is not None:
                self.detector.mark_dead(dr)
            self.policy.mark_dead(dr)
            de = self.entries[dr]
            self.peers.evict(de.connect_host, de.port)
        return Message(MsgType.LEADER_OK, {"epoch": self.epoch})

    def _queue_note_alloc(self, kind: OcmKind, rank: int,
                          nbytes: int) -> None:
        """Hash placement's accounting leg: applied locally when this
        daemon leads, queued for the reaper otherwise — the alloc path
        itself never waits on the leader."""
        note = Message(
            MsgType.NOTE_ALLOC,
            {"kind": WIRE_KIND[kind.value], "rank": rank,
             "device_index": 0, "nbytes": nbytes},
        )
        if self.is_leader:
            self._on_note_alloc(note)
        else:
            with self._acct_lock:
                self._acct_pending.append(note)

    def _drain_accounting(self) -> None:
        """Reaper: flush queued NOTE_ALLOCs to the current leader.
        Unreachable leader ⇒ requeue whole (the books are advisory —
        capacity placement degrades gracefully, and a resync rebuilds
        them from live numbers anyway)."""
        with self._acct_lock:
            pending, self._acct_pending = self._acct_pending, []
        if not pending:
            return
        if self.is_leader:
            for m in pending:
                self._on_note_alloc(m)
            return
        le = self._leader_entry()
        if self._believed_dead(le.rank):
            with self._acct_lock:
                self._acct_pending = pending + self._acct_pending
            return
        for i, m in enumerate(pending):
            try:
                self.peers.request(le.connect_host, le.port, m)
            except (OSError, OcmError):
                with self._acct_lock:
                    self._acct_pending = (
                        pending[i:] + self._acct_pending
                    )
                return

    # -- checkpoint / resume (SURVEY.md §5.4 upgrade) --------------------

    def save_snapshot(self, path: str | None = None) -> None:
        """Persist the registry and the REMOTE_HOST arm's live bytes.

        FROZEN entries are excluded: their payload is already durable in
        the frozen manifest (CRC-trailed extent files), which restore
        re-adopts via ``_adopt_frozen`` — writing them again here would
        double-store every demoted byte and re-couple their durability
        to the snapshot the hard-kill path never writes."""
        from oncilla_tpu.runtime import snapshot as snap

        reg_entries = [e for e in self.registry.snapshot() if not e.frozen]

        def lazy_entries():
            # Arena bytes are read per entry inside the write loop, so peak
            # memory overhead is one entry, not the whole live arena.
            for e in reg_entries:
                data = b""
                if e.kind in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST):
                    data = self.host_arena.read(e.extent, e.nbytes, 0).tobytes()
                yield snap.SnapEntry(
                    alloc_id=e.alloc_id,
                    kind=WIRE_KIND[e.kind.value],
                    device_index=e.device_index,
                    offset=e.extent.offset,
                    nbytes=e.nbytes,
                    origin_rank=e.origin_rank,
                    origin_pid=e.origin_pid,
                    data=data,
                )

        snap.write_file_iter(
            path or self.snapshot_path,
            self.rank, self.registry.counter, len(reg_entries), lazy_entries(),
        )

    def _maybe_restore(self) -> None:
        import os

        from oncilla_tpu.runtime import snapshot as snap

        if not self.snapshot_path or not os.path.exists(self.snapshot_path):
            return
        sp = snap.read_file(self.snapshot_path)
        if sp.rank != self.rank:
            raise OcmError(
                f"snapshot is for rank {sp.rank}, daemon is rank {self.rank}"
            )
        self.registry.restore_counter(sp.id_counter)
        import numpy as np

        for e in sp.entries:
            kind = OcmKind(WIRE_KIND_INV[e.kind])
            if kind in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST):
                ext = self.host_arena.allocator.reserve(e.offset, e.nbytes)
                if e.data:
                    self.host_arena.write(
                        ext, np.frombuffer(e.data, dtype=np.uint8), 0
                    )
            else:
                if not 0 <= e.device_index < len(self.device_books):
                    raise OcmProtocolError(
                        "snapshot device_index out of range for this "
                        f"daemon's ndevices ({e.device_index} >= "
                        f"{len(self.device_books)})"
                    )
                self.device_books[e.device_index].reserve(e.offset, e.nbytes)
            self.registry.insert(
                RegEntry(
                    alloc_id=e.alloc_id,
                    kind=kind,
                    rank=self.rank,
                    device_index=e.device_index,
                    extent=Extent(e.offset, e.nbytes),
                    nbytes=e.nbytes,
                    origin_rank=e.origin_rank,
                    origin_pid=e.origin_pid,
                    lease_expiry=self.registry.new_lease_deadline(),
                )
            )
            # Resync the master's placement accounting.
            note = Message(
                MsgType.NOTE_ALLOC,
                {
                    "kind": e.kind,
                    "rank": self.rank,
                    "device_index": e.device_index,
                    "nbytes": e.nbytes,
                },
            )
            if self.is_leader:
                self._on_note_alloc(note)
            else:
                try:
                    le = self._leader_entry()
                    self.peers.request(le.connect_host, le.port, note)
                except (OSError, OcmConnectError):
                    printd("daemon %d: NOTE_ALLOC to the leader failed",
                           self.rank)
        printd(
            "daemon %d restored %d allocations from snapshot",
            self.rank, len(sp.entries),
        )

    def _adopt_frozen(self) -> None:
        """Warm boot: re-register every surviving frozen extent (fresh
        incarnation, same addr — PR-5/PR-12 fencing covers the epoch
        side). Runs after ``_maybe_restore`` so a snapshot-known id is
        never double-adopted; a hard kill writes no snapshot at all, so
        this path alone is what upholds the durability contract — every
        acked write demoted to FROZEN before the kill comes back.
        Corrupt entries were already quarantined at store open (counted
        ``lost``, never adopted, never served)."""
        if self._frozen is None:
            return
        adopted = 0
        for key in self._frozen.keys():
            if not key.startswith("alloc-"):
                continue  # serving/prefix extents are app-plane state
            meta = self._frozen.meta(key)
            if meta.get("kind") != "alloc":
                continue
            aid = int(meta["alloc_id"])
            try:
                self.registry.lookup(aid)
                continue
            except OcmInvalidHandle:
                pass
            kind = OcmKind(WIRE_KIND_INV[meta["wire_kind"]])
            self.registry.insert(
                RegEntry(
                    alloc_id=aid,
                    kind=kind,
                    rank=self.rank,
                    device_index=0,
                    extent=Extent(0, 0),
                    nbytes=int(meta["nbytes"]),
                    origin_rank=int(meta["origin_rank"]),
                    origin_pid=int(meta["origin_pid"]),
                    lease_expiry=self.registry.new_lease_deadline(),
                    priority=int(meta.get("priority", 1)),
                    frozen=True,
                )
            )
            # Same max-wins counter resync as the snapshot path: ids
            # minted after the restart must never collide with an
            # adopted one. id = (rank << 32) | (counter << 1).
            self.registry.restore_counter((aid & 0xFFFFFFFF) >> 1)
            alloctrace.note_alloc(
                self._trace_scope, aid, int(meta["nbytes"]), kind.name
            )
            adopted += 1
        self.frz_counters["warm_boot_extents"] = adopted
        if adopted:
            obs_journal.record(
                "warm_boot", track=f"daemon-r{self.rank}", rank=self.rank,
                extents=adopted, lost=len(self._frozen.lost),
                incarnation=self.incarnation,
            )
            printd("daemon %d warm-booted %d frozen extents (%d lost)",
                   self.rank, adopted, len(self._frozen.lost))

    def _on_note_alloc(self, msg: Message) -> Message:
        if self.is_leader:
            f = msg.fields
            self.policy.note_alloc(
                Placement(
                    rank=f["rank"],
                    device_index=f["device_index"],
                    kind=OcmKind(WIRE_KIND_INV[f["kind"]]),
                ),
                f["nbytes"],
            )
        return Message(MsgType.FREE_OK, {"alloc_id": 0})

    def _own_resources(self) -> NodeResources:
        return NodeResources(
            rank=self.rank,
            ndevices=self.ndevices,
            device_arena_bytes=self.config.device_arena_bytes,
            host_arena_bytes=self.config.host_arena_bytes,
        )

    def _notify_leader(self, retries: int = 20) -> None:
        """ADD_NODE to the master (notify_rank0 analogue, main.c:144-160;
        the reference SIGINTs itself if the master is absent, mem.c:466-474 —
        here we retry with backoff). A NOT_MASTER redirect re-aims at the
        leader it names (control/): the seed leader may have moved by
        the time a restarted daemon re-announces."""
        msg = Message(
            MsgType.ADD_NODE,
            {
                "rank": self.rank,
                # Announce a peer-reachable address: the bind host may be the
                # wildcard. Short-form entries fall back to the host column.
                "host": self.entries[self.rank].connect_host,
                "port": self.port,
                "ndevices": self.ndevices,
                "device_arena_bytes": self.config.device_arena_bytes,
                "host_arena_bytes": self.config.host_arena_bytes,
            },
        )
        le = self._leader_entry()
        for i in range(retries):
            try:
                self.peers.request(le.connect_host, le.port, msg)
                return
            except OcmRemoteError as e:
                if e.code == int(ErrCode.NOT_MASTER) and getattr(
                    e, "leader_rank", None
                ) is not None:
                    self._adopt_leader_hint(e)
                    le = self._leader_entry()
                    continue
                raise
            except (OSError, OcmConnectError):
                time.sleep(min(0.05 * 2**i, 2.0))
        raise OcmError(
            f"leader daemon unreachable at {le.connect_host}:{le.port}"
        )

    # -- server loops ----------------------------------------------------

    def _accept_loop(self) -> None:
        while self._running.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
                try:  # stream 8 MiB chunks without window stalls
                    conn.setsockopt(socket.SOL_SOCKET, opt, 4 << 20)
                except OSError:
                    pass
            with self._conns_mu:
                self._conns.add(conn)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        """Per-connection handler (inbound_thread analogue, mem.c:319-393).

        ACK coalescing: a DATA_PUT carrying FLAG_MORE is a non-final chunk
        of a burst — it is applied but NOT answered; the first chunk
        without the bit closes the burst and gets ONE reply covering all
        of it (total bytes on success, the burst's first ERROR otherwise).
        Burst state is per-connection local, so concurrent stripes on
        sibling sockets never interact.

        Mux serving (runtime/mux.py): a request carrying FLAG_MUX_TAG has
        a u32 correlation id prefixed to its data tail (stripped FIRST,
        before the trace prefix); its reply carries the same tag back.
        Tagged CONTROL ops are handed to a shared worker pool and may
        complete OUT OF ORDER — one tenant's slow REQ_ALLOC relay no
        longer blocks every other tenant on the shared connection — while
        DATA ops stay inline on this thread (the zero-copy recv-into-
        arena landing and the burst state machine are serve-loop local).
        A per-connection write lock keeps concurrently-sent reply frames
        whole. Untagged traffic is served exactly as before: FIFO, one
        reply per request, byte-identical to the pre-mux protocol.
        """
        # Reusable receive buffer: every inbound bulk payload (DATA_PUT
        # chunks) is fully consumed by its handler before the next recv —
        # the RecvScratch contract. (Tagged control ops handed to the
        # worker pool first detach their payload from the scratch.)
        # Reads are buffered (one kernel recv per ~64 KiB of small
        # frames, not 2-3 per frame) — the small-op serve path is
        # syscall-bound without it; bulk payloads bypass the buffer and
        # keep the recv-into-arena landing.
        scratch = RecvScratch()
        rsock = BufferedSock(conn)
        wlock = make_lock("daemon.conn_wlock")
        cstate = _ConnMuxState()
        # Connection identity for the cancel/ack journal events: tags
        # are per-channel, so the audit invariant scopes them by
        # (daemon track, conn, tag). A plain process-wide counter.
        conn_id = _next_conn_id()
        burst_nbytes = 0        # DATA_PUT_OK bytes accumulated this burst
        burst_err: Message | None = None  # first failure, reported once
        burst_open = False
        burst_t0 = 0.0
        # Reply batching for pipelined tagged traffic: while MORE
        # requests are already buffered (the client streamed a batch),
        # small tagged replies accumulate here and flush as ONE vectored
        # send when the inbound buffer drains — the server-side writev
        # twin of the client's send coalescing. Untagged lockstep flows
        # never batch (one request in hand at a time), so their reply
        # timing is unchanged.
        pending_out: list[bytes] = []

        def flush_replies() -> None:
            if pending_out:
                with wlock:
                    protocol_sendall_vec(conn, pending_out)
                pending_out.clear()

        try:
            while self._running.is_set():
                if pending_out and not rsock.buffered():
                    flush_replies()
                try:
                    msg = recv_msg(rsock, scratch,
                                   data_router=self._route_put_payload)
                except OcmProtocolError as e:
                    # Clean EOF between frames is normal disconnect; any
                    # other decode failure (truncated frame, bad magic,
                    # malformed payload) is hostile/broken input worth a
                    # diagnostic before dropping the connection.
                    if str(e) != "peer closed":
                        printd("daemon %d: dropping conn on malformed "
                               "input: %s", self.rank, e)
                    return
                if self._partitioned:
                    # Chaos isolation: a partitioned host's replies never
                    # arrive — drop the connection mid-exchange so peers
                    # (and probes) see exactly a torn network.
                    return
                # Mux correlation tag: stripped before anything else (it
                # is the OUTERMOST data-tail prefix), remembered so the
                # reply can echo it.
                mux_tag = None
                if msg.flags & FLAG_MUX_TAG:
                    mux_tag, rest = split_tag(msg.data)
                    if mux_tag is not None:
                        msg.data = rest
                        msg.flags &= ~FLAG_MUX_TAG
                        with self._mux_ctr_lock:
                            self._mux_counters["tagged_ops"] += 1
                # Inbound trace context: a FLAG_TRACE_CTX request carries
                # a 16-byte context prefix on its data tail. Strip it
                # BEFORE any length-validating handler sees the payload,
                # and install it around dispatch so this daemon's serve
                # spans (and any hop it forwards) join the client's trace.
                tctx = None
                if msg.flags & FLAG_TRACE_CTX:
                    tctx, rest = obs_trace.split(msg.data)
                    if tctx is not None:
                        msg.data = rest
                        msg.flags &= ~FLAG_TRACE_CTX
                # Propagated time budget (resilience/timebudget.py): a
                # FLAG_DEADLINE request carries its REMAINING budget as
                # a u32-ms prefix (after tag and trace). Re-anchored on
                # THIS host's monotonic clock and installed around
                # dispatch, so expired work is refused typed and every
                # forwarded hop re-attaches the decremented remainder.
                budget = None
                if msg.flags & FLAG_DEADLINE:
                    bud_ms, rest = timebudget.split(msg.data)
                    if bud_ms is not None:
                        msg.data = rest
                        msg.flags &= ~FLAG_DEADLINE
                        budget = timebudget.Budget.from_ms(bud_ms)
                        self.tb_counters["last_budget_ms"] = bud_ms
                is_put = msg.type == MsgType.DATA_PUT
                if burst_open and not is_put:
                    # A sender may not interleave other requests inside an
                    # unfinished burst — the reply stream would desync.
                    burst_nbytes, burst_err, burst_open = 0, None, False
                    self._send_reply(conn, wlock, _err(
                        ErrCode.BAD_MSG,
                        f"{msg.type.name} inside an open DATA_PUT burst",
                    ), mux_tag, conn_id)
                    continue
                if msg.type == MsgType.CANCEL and mux_tag is not None:
                    # Server-side cancellation: served INLINE on the
                    # serve thread (never the pool — a cancel queued
                    # behind the op it revokes would be useless), keyed
                    # by the victim's correlation tag on this same
                    # connection.
                    flush_replies()
                    self._send_reply(
                        conn, wlock,
                        self._cancel_tag(msg.fields["tag"], cstate,
                                         conn_id),
                        mux_tag, conn_id,
                    )
                    continue
                if (
                    mux_tag is not None
                    and not is_put
                    and msg.type != MsgType.DATA_GET
                ):
                    # Out-of-order completion for tagged control ops.
                    if self._serve_tagged_async(conn, wlock, msg, tctx,
                                                mux_tag, cstate, budget,
                                                conn_id):
                        continue
                    # Pool unavailable (daemon stopping): fall through to
                    # the inline path — still correct, just FIFO.
                reply = self._dispatch_guarded(msg, tctx, budget)
                more = is_put and bool(msg.flags & FLAG_MORE)
                if is_put and (more or burst_open):
                    if not burst_open:
                        burst_open, burst_t0 = True, time.perf_counter()
                    if reply.type == MsgType.ERROR:
                        if burst_err is None:
                            burst_err = reply
                    else:
                        burst_nbytes += reply.fields["nbytes"]
                    if more:
                        continue  # reply deferred to the burst's last chunk
                    reply = burst_err or Message(
                        MsgType.DATA_PUT_OK, {"nbytes": burst_nbytes}
                    )
                    if burst_err is None:
                        self.tracer.note_transfer(
                            "put_srv", burst_nbytes,
                            time.perf_counter() - burst_t0, coalesced=True,
                        )
                    burst_nbytes, burst_err, burst_open = 0, None, False
                if (
                    mux_tag is not None
                    and rsock.buffered()
                    and _data_len_of(reply.data) < 4096
                ):
                    obs_journal.record(
                        "mux_reply", track=self.tracer.track,
                        conn=conn_id, tag=mux_tag,
                    )
                    pending_out.append(pack(attach_tag(
                        Message(reply.type, reply.fields, reply.data,
                                reply.flags),
                        mux_tag,
                    )))
                    continue
                flush_replies()
                self._send_reply(conn, wlock, reply, mux_tag, conn_id)
        except OSError:
            pass
        finally:
            with self._conns_mu:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch_guarded(self, msg: Message, tctx,
                          budget: timebudget.Budget | None = None
                          ) -> Message:
        """Dispatch plus the typed-error mapping: every handler failure
        becomes a typed ERROR frame (never a dropped connection). Shared
        by the inline serve loop and the mux worker pool, so the two
        completion paths cannot drift on error semantics.

        ``budget`` is the request's propagated time budget: expired
        work is refused typed BEFORE the handler runs (in particular
        before REQ_ALLOC's quota admission can reserve anything), and
        the budget is ambient during dispatch so forwarded hops carry
        the decremented remainder."""
        if self.serve_delay_s > 0 and msg.type in self.serve_delay_types:
            # Testability hook: the artificially slow daemon the hedge
            # bench and the cancel-storm smoke are built on.
            time.sleep(self.serve_delay_s)
        if budget is not None and budget.expired:
            return self._deadline_err(
                f"{msg.type.name} arrived with its "
                f"{budget.total_ms} ms budget already spent"
            )
        try:
            # OCM_WAITWATCH: the whole dispatch HOLDS the rpc:daemon
            # serve slot, so an outbound dial from a handler shows up
            # as rpc:daemon -> rpc:daemon-adjacent edges — the dynamic
            # twin of the static relay/lock-across-rpc rules.
            with waitwatch.slot(waitwatch.RPC_DAEMON):
                if msg.type in (MsgType.DATA_PUT, MsgType.DATA_GET):
                    op = ("dcn_put_srv" if msg.type == MsgType.DATA_PUT
                          else "dcn_get_srv")
                    with timebudget.use(budget), obs_trace.use_ctx(tctx), \
                            self.tracer.span(
                                op, nbytes=msg.fields["nbytes"]):
                        return self._dispatch(msg)
                elif tctx is not None or budget is not None:
                    # A traced control op gets a serve-side span so the
                    # exported trace shows the daemon hop, not just the
                    # client's view of the round-trip; a budgeted one
                    # keeps its remainder ambient for the hops it
                    # forwards.
                    with timebudget.use(budget), obs_trace.use_ctx(tctx), \
                            self.tracer.span(
                                "srv_" + msg.type.name.lower()):
                        return self._dispatch(msg)
                else:
                    return self._dispatch(msg)
        except OcmDeadlineExceeded as e:
            return self._deadline_err(str(e))
        except OcmOutOfMemory as e:
            return _err(ErrCode.OOM, str(e))
        except OcmQuotaExceeded as e:
            return _err(ErrCode.QUOTA_EXCEEDED, str(e))
        except OcmAdmissionDenied as e:
            return _err(ErrCode.ADMISSION_DENIED, str(e))
        except OcmBusy as e:
            # Retryable back-pressure: the server-suggested backoff
            # rides as a u32 (ms) data tail — invisible to peers that
            # don't know the code.
            return _err(ErrCode.BUSY, str(e),
                        struct.pack("<I", e.retry_after_ms))
        except OcmReplicaUnavailable as e:
            return _err(ErrCode.REPLICA_UNAVAILABLE, str(e))
        except OcmNotPrimary as e:
            return _err(ErrCode.NOT_PRIMARY, str(e))
        except OcmMoved as e:
            # Live-migration redirect: the new owner rank rides as an
            # i64 data tail (invisible to old peers).
            return _err(ErrCode.MOVED, str(e), struct.pack("<q", e.rank))
        except OcmBoundsError as e:
            return _err(ErrCode.BOUNDS, str(e))
        except OcmInvalidHandle as e:
            return _err(ErrCode.BAD_ALLOC_ID, str(e))
        except OcmPlacementError as e:
            return _err(ErrCode.PLACEMENT, str(e))
        except OcmRemoteError as e:
            # A relayed hop's typed rejection (REQ_ALLOC proxied to the
            # leader, DO_FREE to an owner) keeps its code — clients
            # switch on it (BUSY backoff, failover ladder), so
            # flattening to UNKNOWN here would break them one hop out.
            # BUSY re-carries its backoff tail.
            code = (
                ErrCode(e.code)
                if e.code in ErrCode._value2member_map_
                else ErrCode.UNKNOWN
            )
            if code == ErrCode.BUSY:
                tail = struct.pack("<I", getattr(e, "retry_after_ms", 0))
            elif code == ErrCode.MOVED and hasattr(e, "moved_to_rank"):
                # Relayed migration redirects keep their rank tail —
                # the redirect is useless without it.
                tail = struct.pack("<q", e.moved_to_rank)
            else:
                tail = b""
            return _err(code, e.detail, tail)
        except OcmError as e:
            return _err(ErrCode.UNKNOWN, str(e))
        except Exception as e:  # noqa: BLE001 — always answer with a
            # typed ERROR frame rather than killing the connection.
            return _err(ErrCode.UNKNOWN, f"{type(e).__name__}: {e}")

    def _deadline_err(self, detail: str) -> Message:
        """The typed DEADLINE_EXCEEDED rejection + its accounting (one
        place, so the pre-dispatch refusal and the mid-dispatch raise
        cannot drift on counters or journal shape)."""
        self.tb_counters["deadline_exceeded"] += 1
        obs_journal.record(
            "deadline_exceeded", track=self.tracer.track, detail=detail,
        )
        return _err(ErrCode.DEADLINE_EXCEEDED, detail)

    def _send_reply(self, conn: socket.socket, wlock, reply: Message,
                    tag: int | None, conn_id: int = -1) -> None:
        """One reply frame, tag echoed, whole under the connection's
        write lock (the mux pool's out-of-order completions share the
        socket with the serve loop). Tagged replies journal a
        ``mux_reply`` event — the evidence stream the
        no-ack-after-cancel-ack audit invariant walks."""
        if tag is not None:
            obs_journal.record(
                "mux_reply", track=self.tracer.track, conn=conn_id,
                tag=tag,
            )
            reply = attach_tag(
                Message(reply.type, reply.fields, reply.data, reply.flags),
                tag,
            )
        with wlock:
            send_msg(conn, reply)  # ocm-lint: allow[blocking-call-under-lock]
            # — wlock is a leaf serializing exactly this socket's writes.

    def _ensure_mux_pool(self):
        with self._mux_pool_lock:
            if self._mux_pool is None and self._running.is_set():
                from concurrent.futures import ThreadPoolExecutor

                self._mux_pool = ThreadPoolExecutor(
                    max_workers=_MUX_POOL_WORKERS,
                    thread_name_prefix=f"d{self.rank}-mux",
                )
            return self._mux_pool

    def _serve_tagged_async(self, conn, wlock, msg: Message, tctx,
                            tag: int, cstate, budget=None,
                            conn_id: int = -1) -> bool:
        """Queue one tagged control op on the mux worker pool. Returns
        False when the pool cannot take it (daemon stopping) — the
        caller serves inline instead."""
        pool = self._ensure_mux_pool()
        if pool is None:
            return False
        if not isinstance(msg.data, (bytes, bytearray)):
            # Detach from the connection's RecvScratch: the serve loop
            # recvs the NEXT frame while the worker still reads this one.
            msg.data = bytes(msg.data)
        seq = cstate.note_start(tag)
        with self._mux_ctr_lock:
            self._mux_counters["inflight"] += 1
            self._mux_counters["peak_inflight"] = max(
                self._mux_counters["peak_inflight"],
                self._mux_counters["inflight"],
            )
        try:
            pool.submit(
                self._serve_tagged, conn, wlock, msg, tctx, tag, cstate,
                seq, budget, conn_id, time.monotonic(),
            )
        except RuntimeError:  # pool shut down between check and submit
            cstate.note_done(seq)
            cstate.finish_tag(tag)
            with self._mux_ctr_lock:
                self._mux_counters["inflight"] -= 1
            return False
        return True

    def _serve_tagged(self, conn, wlock, msg: Message, tctx, tag: int,
                      cstate, seq: int, budget=None,
                      conn_id: int = -1, t_enq: float = 0.0) -> None:
        # A cancel that landed while this op sat QUEUED revokes it
        # before any side effect: nothing dispatched, nothing reserved,
        # no reply (the client already tombstoned the tag).
        if cstate.take_if_cancelled(tag):
            ooo = cstate.note_done(seq)
            with self._mux_ctr_lock:
                self._mux_counters["inflight"] -= 1
                if ooo:
                    self._mux_counters["ooo"] += 1
            self.tb_counters["cancel_drops"] += 1
            obs_journal.record(
                "cancel_drop", track=self.tracer.track, conn=conn_id,
                tag=tag, stage="queued",
            )
            return
        if t_enq and obs_journal.enabled():
            # Time spent queued behind the bounded mux worker pool. The
            # phase binds to the CLIENT op's wire ctx (tctx): the wait
            # precedes the serve span, so it falls in the client span's
            # self time — exactly where the attributor must charge it.
            obs_journal.phase(
                "daemon_queue", time.monotonic() - t_enq, ctx=tctx,
                track=self.tracer.track,
            )
        try:
            # OCM_WAITWATCH: this thread occupies a bounded mux-pool
            # slot for the dispatch — the resource the static
            # pool-stratification rule strata-checks.
            with waitwatch.slot(waitwatch.MUX_SLOT):
                reply = self._dispatch_guarded(msg, tctx, budget)
        finally:
            ooo = cstate.note_done(seq)
            with self._mux_ctr_lock:
                self._mux_counters["inflight"] -= 1
                if ooo:
                    self._mux_counters["ooo"] += 1
        if not cstate.finish_tag(tag):
            # A binding cancel won the race mid-dispatch: suppress the
            # reply — the cancel-ack already told the client "revoked",
            # so an ack here would be the exact violation the
            # no-ack-after-cancel-ack invariant audits. A completed
            # REQ_ALLOC is unwound through the ordinary free path so
            # the reserve -> commit accounting drains.
            self.tb_counters["cancel_drops"] += 1
            obs_journal.record(
                "cancel_drop", track=self.tracer.track, conn=conn_id,
                tag=tag, stage="completed",
            )
            if reply.type == MsgType.ALLOC_RESULT:
                self.tb_counters["cancel_frees"] += 1
                self._dispatch_guarded(Message(
                    MsgType.REQ_FREE,
                    {"alloc_id": reply.fields["alloc_id"],
                     "rank": reply.fields["rank"]},
                ), None)
            return
        try:
            self._send_reply(conn, wlock, reply, tag, conn_id)
        except OSError:
            pass  # connection died; the serve loop's own path closes it

    def _cancel_tag(self, victim: int, cstate: _ConnMuxState,
                    conn_id: int) -> Message:
        """Serve one CANCEL: revoke the victim tag on this connection's
        worker-pool state and ack with the outcome. The ``cancel_ack``
        journal event (recorded BEFORE the ack leaves) is the anchor of
        the no-ack-after-cancel-ack audit invariant; in-flight DATA
        legs are inline on the serve thread — they drained to their
        chunk boundary before this CANCEL could even be read, which is
        exactly the drain contract."""
        revoked = cstate.cancel(victim)
        self.tb_counters["cancels"] += 1
        if revoked:
            self.tb_counters["cancels_revoked"] += 1
        obs_journal.record(
            "cancel", track=self.tracer.track, conn=conn_id,
            tag=victim, revoked=int(revoked),
        )
        obs_journal.record(
            "cancel_ack", track=self.tracer.track, conn=conn_id,
            tag=victim, revoked=int(revoked),
        )
        return Message(
            MsgType.CANCEL_OK, {"tag": victim, "revoked": int(revoked)}
        )

    def _on_cancel(self, msg: Message) -> Message:
        """CANCEL outside a mux channel (a lockstep or untagged sender):
        with one request in flight per connection there is nothing to
        revoke — answer honestly. The real path is the serve loop's
        inline branch, which owns the connection's tag state."""
        self.tb_counters["cancels"] += 1
        return Message(
            MsgType.CANCEL_OK, {"tag": msg.fields["tag"], "revoked": 0}
        )

    def _mux_meta(self) -> dict:
        """Mux serving counters for STATUS / STATUS_PROM / the obs
        cluster table's in-flight column."""
        with self._mux_ctr_lock:
            return dict(self._mux_counters)

    def _reaper_loop(self) -> None:
        """Reclaim expired leases — the capability the reference left as a
        TODO (main.c:6-7): no heartbeat => allocations freed."""
        while self._running.is_set():
            time.sleep(self.config.heartbeat_s)
            for e in self.registry.expired():
                printd(
                    "daemon %d reaping expired alloc %d (origin pid %d)",
                    self.rank, e.alloc_id, e.origin_pid,
                )
                try:
                    self._do_free_local(e.alloc_id)
                except OcmInvalidHandle:
                    continue
                self.registry.note_reclaim()
                obs_journal.record(
                    "lease_reclaim", track=self.tracer.track,
                    alloc_id=e.alloc_id, nbytes=e.nbytes,
                    origin_pid=e.origin_pid, origin_rank=e.origin_rank,
                )
            # QoS (qos/): pressure eviction under the arena watermarks,
            # stale-tenant pruning, and the load-aware placement feed.
            # Each guarded — a QoS hiccup must never kill the reaper.
            try:
                self._pressure_evict()
                self.qos.prune_stale()
            except Exception as e:  # noqa: BLE001 — see above
                printd("daemon %d: pressure evict failed: %s", self.rank, e)
            try:
                self._feed_load_stats()
            except Exception as e:  # noqa: BLE001 — telemetry feed is
                # best-effort; placement falls back to capacity order
                printd("daemon %d: load feed failed: %s", self.rank, e)
            if self._plane_unsynced:
                self._sync_plane_endpoint()
            if self._member_unsynced:
                try:
                    self._sync_members()
                except Exception as e:  # noqa: BLE001 — gossip must never
                    # kill the reaper; unsynced peers retry next tick
                    printd("daemon %d: member sync failed: %s", self.rank, e)
            # Decentralized control plane (control/): replicate the
            # master state to standbys, retry LEADER_UPDATE stragglers,
            # flush hash placement's deferred accounting. Each guarded —
            # leadership machinery must never kill the reaper.
            try:
                if self.config.standby_masters > 0 and self.is_leader:
                    self._push_master_state()
                if self._leader_unsynced:
                    self._sync_leader_update()
                self._drain_accounting()
            except Exception as e:  # noqa: BLE001 — see above
                printd("daemon %d: leader tick failed: %s", self.rank, e)
            self._prune_tombstones()
            try:
                self._detector_tick()
            except Exception as e:  # noqa: BLE001 — liveness must never
                # kill the reaper thread (leases matter more than probes)
                printd("daemon %d: detector tick failed: %s", self.rank, e)

    # -- multi-tenant QoS (qos/) -----------------------------------------

    def _pressure_evict(self) -> None:
        """Priority eviction under arena pressure (Borg-style tiers):
        when host occupancy crosses the high watermark, free extents in
        victim order — expired first, then priority ascending, oldest
        lease first — until occupancy falls below the LOW watermark
        (hysteresis) or victims run out. The invariant this PRESERVES:
        an ACTIVE (lease-current) extent above priority 0 is never
        evicted; only the low class is preemptible while alive. Runs on
        the owner, and only over entries this rank is primary for (the
        chain free fans out), so replica copies never fork."""
        cap = self.config.host_arena_bytes
        if cap <= 0:
            return
        live = self.host_arena.allocator.bytes_live
        if live / cap < self.config.arena_high_pct / 100.0:
            return
        low_bytes = cap * self.config.arena_low_pct / 100.0
        now = time.monotonic()
        for e in self.registry.eviction_candidates(self.rank, now):
            if self.host_arena.allocator.bytes_live <= low_bytes:
                break
            active = e.lease_expiry >= now
            if active and e.priority > PRIO_LOW:
                # Victim queue is sorted, but the guard stays explicit:
                # the invariant must hold even if the ordering changes.
                continue
            # Demote-to-FROZEN leg (persist/): with a frozen store
            # attached, a victim spills to disk instead of being
            # destroyed — same victim order, same invariant, but the
            # payload survives and the first client data op thaws it
            # back. Replicated entries keep the destroy path (a frozen
            # primary under a live chain would fork ownership), as does
            # anything mid-migration. A full/unwritable store falls
            # through to the pre-FROZEN destroy.
            if (self._frozen is not None and not e.chain
                    and not e.migrating
                    and self._demote_to_frozen(e, active)):
                continue
            try:
                self._do_free_local(e.alloc_id)
            except OcmInvalidHandle:
                continue  # raced with an explicit free
            except (OSError, OcmError) as exc:
                printd("daemon %d: eviction of %d failed: %s",
                       self.rank, e.alloc_id, exc)
                continue
            self.qos.note_eviction(e.priority, active)
            self.registry.note_reclaim()
            obs_journal.record(
                "qos_evict", track=self.tracer.track,
                alloc_id=e.alloc_id, priority=e.priority, active=active,
                nbytes=e.nbytes, origin_pid=e.origin_pid,
                destroyed=True,
            )
            printd(
                "daemon %d evicted alloc %d under pressure "
                "(priority %d, %s, %d B)",
                self.rank, e.alloc_id, e.priority,
                "active" if active else "expired", e.nbytes,
            )

    def _demote_to_frozen(self, e, active: bool) -> bool:
        """Spill one eviction victim's bytes to the frozen store and
        release its arena extent, keeping the registry entry (marked
        ``frozen``) so the id stays valid and leases keep renewing.
        Returns False — caller destroys as before — when the store
        refuses (budget) or the write fails; the entry is untouched in
        that case (the write is atomic, tmp+replace)."""
        with self._frz_lock:
            if e.frozen:
                return True  # raced with another demote
            try:
                data = self.host_arena.read(e.extent, e.nbytes, 0).tobytes()
                self._frozen.write(
                    f"alloc-{e.alloc_id}", data,
                    meta={
                        "kind": "alloc",
                        "alloc_id": e.alloc_id,
                        "wire_kind": WIRE_KIND[e.kind.value],
                        "nbytes": e.nbytes,
                        "origin_rank": e.origin_rank,
                        "origin_pid": e.origin_pid,
                        "priority": e.priority,
                    },
                )
            except (OSError, OcmError) as exc:
                printd("daemon %d: demote of %d to frozen declined: %s",
                       self.rank, e.alloc_id, exc)
                return False
            self.host_arena.free(e.extent)
            e.extent = Extent(0, 0)
            e.frozen = True
        self.frz_counters["demotes"] += 1
        self.qos.note_demotion(e.priority, active)
        obs_journal.record(
            "tier_demote", track=self.tracer.track,
            alloc_id=e.alloc_id, priority=e.priority, active=active,
            nbytes=e.nbytes, origin_pid=e.origin_pid,
            dst="frozen", destroyed=False,
        )
        printd(
            "daemon %d demoted alloc %d to FROZEN under pressure "
            "(priority %d, %s, %d B)",
            self.rank, e.alloc_id, e.priority,
            "active" if active else "expired", e.nbytes,
        )
        return True

    def _thaw(self, e, _retried: bool = False) -> None:
        """Promote a frozen entry back into the host arena (the first
        client data op's page-fault). Rides the existing data-plane
        handlers — a FROZEN extent is just a slow read at its owner, so
        clients need zero new wire surface. On an arena-full fault the
        pressure evictor runs once OUTSIDE ``_frz_lock`` (its free
        fan-out may dial peers; it may demote OTHER victims to make
        room) and the thaw retries once; a corrupt frozen file surfaces
        as the typed OcmFrozenCorrupt, never as garbage bytes."""
        import numpy as np

        with self._frz_lock:
            if not e.frozen:
                return  # raced with another thaw
            data = self._frozen.read_bytes(f"alloc-{e.alloc_id}")
            try:
                extent = self.host_arena.alloc(e.nbytes)
            except OcmOutOfMemory:
                if _retried:
                    raise
                extent = None
            if extent is not None:
                self.host_arena.write(
                    extent, np.frombuffer(data, dtype=np.uint8), 0
                )
                e.extent = extent
                e.frozen = False
                self._frozen.delete(f"alloc-{e.alloc_id}")
        if extent is None:
            self._pressure_evict()
            self._thaw(e, _retried=True)
            return
        self.frz_counters["promotes"] += 1
        obs_journal.record(
            "tier_promote", track=self.tracer.track,
            alloc_id=e.alloc_id, priority=e.priority,
            nbytes=e.nbytes, origin_pid=e.origin_pid, src="frozen",
        )

    def _feed_load_stats(self) -> None:
        """Rank-0, policy="loadaware" only: refresh the placement
        policy's per-rank load scores from each daemon's live stats —
        its own locally, peers via the same STATUS the obs CLI polls."""
        observe = getattr(self.policy, "observe", None)
        if not self.is_leader or observe is None:
            return
        now = time.monotonic()
        if now - self._last_load_poll < self.config.loadaware_poll_s:
            return
        self._last_load_poll = now
        observe(
            self.rank,
            live_bytes=self.host_arena.allocator.bytes_live,
            **self._own_load_sample(),
        )
        for e in self.entries:
            if e.rank == self.rank or e.port == 0:
                continue
            if self._believed_dead(e.rank):
                continue
            try:
                r = self.peers.request(
                    e.connect_host, e.port, Message(MsgType.STATUS, {})
                )
            except (OSError, OcmError):
                continue  # detector owns liveness; skip this round
            gbps, p99 = 0.0, 0.0
            if r.data:
                import json

                try:
                    tail = json.loads(bytes(r.data))
                except (ValueError, UnicodeDecodeError):
                    tail = {}
                ops = (tail.get("dcn") or {}).get("ops") or {}
                p99 = max(
                    (v.get("p99_us", 0.0) for v in ops.values()),
                    default=0.0,
                )
                transfers = (tail.get("dcn") or {}).get("transfers") or []
                if transfers:
                    gbps = transfers[-1].get("gbps", 0.0)
            observe(
                e.rank,
                live_bytes=r.fields.get("host_bytes_live", 0),
                gbps=gbps, p99_us=p99,
            )

    def _own_load_sample(self) -> dict:
        ops = {
            k: v for k, v in self.tracer.snapshot().items()
            if k.startswith("dcn_")
        }
        transfers = self.tracer.transfers(last=1)
        return {
            "gbps": transfers[-1].get("gbps", 0.0) if transfers else 0.0,
            "p99_us": max(
                (v.get("p99_us", 0.0) for v in ops.values()), default=0.0
            ),
        }

    # -- failure detection (resilience/detector.py) ----------------------

    def _probe_ranks(self) -> list[int]:
        """Star topology + one neighbor: the LEADER probes everyone (it
        is the arbiter); every other rank probes the leader plus its
        next neighbor, so each non-master is watched by a second witness
        whose SUSPECT report gives the leader an early arbitration
        trigger. Total probe load stays O(n) per interval.

        Election evidence (control/): once a standby believes the
        leader dead it additionally probes every SMALLER live rank —
        the election rule is lowest-live-rank, so a waiting standby
        must be able to discover that the would-be winner is dead too,
        or the election would stall on a rank nobody was watching."""
        det = self.detector
        allowed = set(det.probe_targets())
        lr = self.leader_rank
        if self.rank == lr:
            return sorted(allowed)
        n = len(self.entries)
        targets = {lr}
        r = (self.rank + 1) % n
        while r in (self.rank, lr):
            r = (r + 1) % n
            if r == self.rank:  # 2-node cluster: the leader is the only peer
                break
        if r not in (self.rank, lr):
            targets.add(r)
        if self.config.standby_masters > 0 and self._believed_dead(lr):
            targets.update(
                e.rank for e in self.entries
                if e.rank < self.rank and e.rank != lr and e.port
                and not self.entries.has_left(e.rank)
            )
        return sorted(t for t in targets if t in allowed)

    def _detector_tick(self) -> None:
        det = self.detector
        if det is None or self._fenced or not self._running.is_set():
            return
        now = time.monotonic()
        if now - self._last_probe < self.config.detect_interval_s:
            return
        self._last_probe = now
        for r in self._probe_ranks():
            e = self.entries[r]
            if e.port == 0:
                continue  # ephemeral-port test daemon not started yet
            res = (
                None if self._partitioned  # chaos isolation: packets drop
                else probe(
                    e.connect_host, e.port, self.rank, self.epoch,
                    self.incarnation,
                    timeout=self.config.probe_timeout_s,
                )
            )
            if not self._running.is_set():
                return
            if isinstance(res, DeadVerdict):
                # The peer says WE were declared dead. Binding only when
                # its authority outranks ours — a deposed leader's stale
                # claim (lower leader_epoch) is ignored, while the real
                # leader's verdict fences a healed partitioned daemon.
                if res.outranks(self.leader_epoch, self.epoch):
                    self._fence(self.epoch)
                    return
                continue  # deluded claimant: neither alive nor dead news
            if res is not None:
                self._adopt_epoch(res[0])
                prev = det.record_ok(r, res[1])
                if prev == PeerState.DEAD:
                    obs_journal.record(
                        "node_recovered", track=self.tracer.track, rank=r,
                    )
                    if self.is_leader:
                        self.policy.mark_alive(r)
                continue
            st = det.record_fail(r)
            if st == PeerState.DEAD:
                # Evict pooled connections NOW: stale sockets to a dead
                # rank otherwise fail lazily, one costly error per lease.
                self.peers.evict(e.connect_host, e.port)
            if st == PeerState.SUSPECT and not self.is_leader:
                le = self._leader_entry()
                try:
                    self.peers.request(
                        le.connect_host, le.port,
                        Message(MsgType.SUSPECT_NODE,
                                {"rank": r, "reporter": self.rank,
                                 "epoch": self.epoch}),
                    )
                except (OSError, OcmError):
                    printd("daemon %d: SUSPECT report for %d failed",
                           self.rank, r)
            elif st == PeerState.DEAD and self.is_leader:
                self._failover.node_dead(r)
        # Election check (control/): a standby whose detector holds the
        # LEADER dead runs the lowest-live-rank rule each tick until a
        # LEADER_UPDATE lands or it wins.
        if (
            self.config.standby_masters > 0
            and not self.is_leader
            and not self._fenced
            and self._believed_dead(self.leader_rank)
        ):
            self._maybe_elect()

    # -- trace-aware peer forwarding -------------------------------------

    def _peer_caps_for(self, host: str, port: int) -> int:
        """Negotiated capability bits for the daemon at (host, port),
        probed once per address with a CONNECT offering FLAG_CAP_TRACE
        and FLAG_CAP_QOS (one probe covers both relay concerns: trace
        prefixes and priority tails). Un-upgraded v2 peers and the
        native C++ daemon echo flags=0 — decline by silence — and this
        daemon then ships plain frames to them. Probe failures are NOT
        cached (the peer may simply be restarting); the forwarded
        request itself will surface the real error."""
        key = (host, port)
        with self._peer_caps_lock:
            caps = self._peer_caps.get(key)
        if caps is not None:
            return caps
        import os as _os

        offer = FLAG_CAP_TRACE | FLAG_CAP_QOS | FLAG_CAP_DEADLINE
        try:
            r = self.peers.request(host, port, Message(
                MsgType.CONNECT,
                {"pid": _os.getpid(), "rank": self.rank},
                flags=offer,
            ))
            caps = (
                r.flags & offer
                if r.type == MsgType.CONNECT_CONFIRM else 0
            )
        except (OSError, OcmError):
            return 0
        with self._peer_caps_lock:
            self._peer_caps[key] = caps
        return caps

    def _peer_request(self, host: str, port: int, msg: Message) -> Message:
        """peers.request plus trace/budget propagation: when a trace
        context is ambient (this request relays a traced serve) and the
        peer granted FLAG_CAP_TRACE, the context rides the forwarded
        message — the hop that stitches client span → local daemon span
        → peer daemon span. When a time budget is ambient (this serve
        arrived with FLAG_DEADLINE) the REMAINING budget rides too —
        decremented by this hop's observed elapsed time, since the
        remainder is computed at send time — and an already-expired
        budget refuses the relay outright instead of burning a round
        trip on work the origin has given up on. Attaches to a shallow
        copy: relay loops reuse one Message for several peers."""
        valid = VALID_FLAGS.get(msg.type, 0)
        # Budget FIRST (it is the innermost prefix: receivers strip tag,
        # then trace, then deadline), trace second, so the wire layout
        # matches the strip order.
        bud = timebudget.current()
        timeout: float | None = None
        if bud is not None and valid & FLAG_DEADLINE:
            if bud.expired:
                raise OcmDeadlineExceeded(
                    f"relay of {msg.type.name} to {host}:{port}: "
                    f"{bud.total_ms} ms budget exhausted before the hop"
                )
            # The remainder bounds the WHOLE exchange, not just the wire
            # attach: without it a relay against a SIGSTOPped peer sat
            # in a blocked recv until the pool's transport default,
            # long past the origin's deadline (the PR-15 bug class the
            # unbounded-blocking analysis now gates).
            # Floor of 1 ms: remaining_s() can hit 0.0 in the race
            # window after the expired check, and settimeout(0) would
            # flip the socket non-blocking instead of timing out.
            timeout = max(bud.remaining_s(), 0.001)
            if self._peer_caps_for(host, port) & FLAG_CAP_DEADLINE:
                msg = timebudget.attach(
                    Message(msg.type, msg.fields, msg.data, msg.flags),
                    bud, FLAG_DEADLINE,
                )
        ctx = obs_trace.current()
        if (
            ctx is not None
            and valid & FLAG_TRACE_CTX
            and self._peer_caps_for(host, port) & FLAG_CAP_TRACE
        ):
            msg = obs_trace.attach(
                Message(msg.type, msg.fields, msg.data, msg.flags),
                ctx, FLAG_TRACE_CTX,
            )
        if timeout is not None:
            return self.peers.request(host, port, msg, timeout=timeout)
        # No ambient budget => no deadline to thread; the pool's
        # transport default bounds the exchange. Kept as a separate
        # call (not timeout=None) so test seams that wrap
        # peers.request with a (host, port, msg) signature keep
        # working on un-budgeted paths.
        return self.peers.request(host, port, msg)  # ocm-lint: allow[unbounded-blocking]

    # -- dispatch --------------------------------------------------------

    def _dispatch(self, msg: Message) -> Message:
        if self.handler_delay_s > 0 and msg.type in self.handler_delay_types:
            time.sleep(self.handler_delay_s)
        if self._fenced and msg.type in _FENCED_REJECT:
            # A fenced daemon outlived its own DEAD verdict: its replicas
            # were promoted under a newer epoch, so serving data or
            # granting extents here would be split-brain. Clients treat
            # STALE_EPOCH as a failover signal and retry the chain.
            return _err(
                ErrCode.STALE_EPOCH,
                f"rank {self.rank} fenced at epoch {self.epoch}",
            )
        h = _HANDLERS.get(msg.type)
        if h is None:
            return _err(ErrCode.BAD_MSG, f"unhandled message {msg.type.name}")
        return h(self, msg)

    # CONNECT: app attach (process_msg MSG_CONNECT analogue, main.c:58-103).
    def _on_connect(self, msg: Message) -> Message:
        printd("daemon %d: app pid %d connected", self.rank, msg.fields["pid"])
        # QoS profile declaration (qos/): a FLAG_CAP_QOS offer may carry
        # the app's (priority, quota_bytes, quota_handles) as a
        # FLAG_QOS_TAIL data tail. Registered BEFORE the echo so the
        # app's very first REQ_ALLOC already runs under its profile.
        if msg.flags & FLAG_CAP_QOS and msg.flags & FLAG_QOS_TAIL:
            prof = unpack_profile(msg.data)
            if prof is not None:
                self.qos.register(
                    msg.fields["pid"], msg.fields["rank"], *prof
                )
        # Capability negotiation: grant exactly the offered bits we
        # implement. Peers that never offer (old clients, the C++ daemon's
        # own dials) get flags=0 and the lockstep protocol unchanged.
        # FLAG_CAP_MUX (tagged request multiplexing) is granted unless
        # OCM_MUX_SERVE=0 pins this daemon to the un-upgraded behavior
        # (the interop tests' decline-by-silence lever).
        reply = Message(
            MsgType.CONNECT_CONFIRM,
            {
                "rank": self.rank,
                "nnodes": self.policy.nnodes if self.is_leader
                else len(self.entries),
            },
            flags=msg.flags
            & (FLAG_CAP_COALESCE | FLAG_CAP_TRACE | FLAG_CAP_REPLICA
               | FLAG_CAP_QOS | FLAG_CAP_DEADLINE
               | (FLAG_CAP_MUX if self.config.mux_serve else 0)),
        )
        if reply.flags & FLAG_CAP_MUX:
            with self._mux_ctr_lock:
                self._mux_counters["conns"] += 1
        # Fabric negotiation (fabric/): an offered FLAG_CAP_FABRIC is
        # granted only when this daemon actually registered a fabric —
        # the grant carries the descriptor tail the client needs to
        # prove reachability (attach the segment). Un-offered CONNECTs
        # ship the reply unchanged, so the default wire stays
        # byte-for-byte pre-fabric.
        if msg.flags & FLAG_CAP_FABRIC:
            desc = {n: f.descriptor() for n, f in self.fabrics.items()}
            if desc:
                import json

                reply.flags |= FLAG_CAP_FABRIC
                reply.data = json.dumps(
                    desc, separators=(",", ":")
                ).encode()
                self.fabric_counters["selected_shm"] += 1
            else:
                self.fabric_counters["selected_tcp"] += 1
        return reply

    def _on_disconnect(self, msg: Message) -> Message:
        """Immediate reclamation on app disconnect instead of waiting out the
        lease (the reference daemon tracks connected apps and frees on
        disconnect, main.c:46-47,58-103). The app reports which owner ranks
        hold its remote allocations ("owners", tracked app-side where the
        handles live), so the fan-out is O(owners); a crashed app never sends
        DISCONNECT and falls back to the lease reaper."""
        pid = msg.fields["pid"]
        # Terminal event for the app's lease-renewal chain: the auditor
        # requires every renewing app to end in disconnect/free/reclaim.
        obs_journal.record(
            "app_disconnect", track=self.tracer.track, pid=pid,
        )
        self._reclaim_app_local(pid, self.rank)
        # The tenant's whole QoS state goes with it — quota give-back for
        # remote-owned allocations the origin ledger still remembered.
        self.qos.drop_app(pid, self.rank)
        for r in _parse_owners(msg.fields.get("owners", "")):
            if r == self.rank or not 0 <= r < len(self.entries):
                continue
            e = self.entries[r]
            try:
                self._peer_request(
                    e.connect_host, e.port,
                    Message(MsgType.RECLAIM_APP,
                            {"pid": pid, "rank": self.rank}),
                )
            except (OSError, OcmError):
                printd("daemon %d: RECLAIM_APP to %d failed (lease reaper "
                       "is the backstop)", self.rank, r)
        return Message(MsgType.CONNECT_CONFIRM, {"rank": self.rank, "nnodes": 0})

    def _on_reclaim_app(self, msg: Message) -> Message:
        n = self._reclaim_app_local(msg.fields["pid"], msg.fields["rank"])
        return Message(MsgType.RECLAIM_APP_OK, {"count": n})

    def _reclaim_app_local(self, origin_pid: int, origin_rank: int) -> int:
        n = 0
        for e in self.registry.for_app(origin_pid, origin_rank):
            printd("daemon %d reclaiming alloc %d of disconnected app %d",
                   self.rank, e.alloc_id, origin_pid)
            try:
                self._do_free_local(e.alloc_id)
                n += 1
            except OcmInvalidHandle:  # raced with an explicit free
                pass
        return n

    # ADD_NODE: only the master records membership (alloc_add_node,
    # alloc.c:60-74).
    def _on_add_node(self, msg: Message) -> Message:
        if not self.is_leader:
            return self._not_master_err("ADD_NODE")
        f = msg.fields
        self.policy.add_node(
            NodeResources(
                rank=f["rank"],
                ndevices=f["ndevices"],
                device_arena_bytes=f["device_arena_bytes"],
                host_arena_bytes=f["host_arena_bytes"],
            )
        )
        # A (re)joining daemon is a fresh process: clear any DEAD verdict
        # (revival happens HERE, never via pings — see _on_ping).
        if self.detector is not None:
            self.detector.mark_alive(f["rank"])
        # Record the peer's address for forwarding. A nodefile-provided
        # connect address wins over the announced hostname (the announcement
        # carries the daemon's bind host, which may not be routable).
        if 0 <= f["rank"] < len(self.entries):
            prev = self.entries[f["rank"]]
            self.entries[f["rank"]] = NodeEntry(
                f["rank"], f["host"], f["port"], prev.addr
            )
        # A (re)joining daemon starts with no in-memory plane endpoint:
        # queue it for the reaper's gossip so relays work there promptly
        # (the client's periodic re-registration is the slower backstop).
        # Same bounds guard as the entries update above: an out-of-range
        # rank would IndexError inside the reaper and kill it.
        if self.plane_addr is not None and 0 <= f["rank"] < len(self.entries):
            with self._plane_sync_lock:
                self._plane_unsynced.add(f["rank"])
        return Message(MsgType.ADD_NODE_OK, {"nnodes": self.policy.nnodes})

    # REQ_ALLOC: non-masters proxy the request to rank 0 (the placement leg,
    # mem.c:128); rank 0 places (alloc_find analogue) then drives the
    # DO_ALLOC leg to the owner and returns the complete handle
    # (msg_send_req_alloc analogue, mem.c:234-260). QoS (qos/) wraps the
    # whole path: size validation first, then quota admission at the
    # app's ORIGIN daemon (the one that holds its profile), then the
    # rank-0 back-pressure check inside _place_alloc.
    def _on_req_alloc(self, msg: Message) -> Message:
        f = msg.fields
        nbytes = f["nbytes"]
        kind = OcmKind(WIRE_KIND_INV[f["kind"]])
        # Daemon-side size validation: a zero-byte request has no valid
        # extent (it previously surfaced as an untyped ValueError deep in
        # the owner's arena), and a request above every node's arena can
        # NEVER be sited — reject both up front, reserving nothing.
        if nbytes <= 0:
            raise OcmPlacementError(
                f"invalid allocation size {nbytes}: must be > 0"
            )
        if self.is_leader:
            cap = self.policy.max_capacity(kind)
            if cap and nbytes > cap:
                raise OcmOutOfMemory(
                    f"{nbytes} B of {kind.value} exceeds every node's "
                    f"arena capacity (largest is {cap} B)"
                )
        app = (f["pid"], f["orig_rank"])
        local_app = f["orig_rank"] == self.rank
        if local_app:
            # Admission: reserve against the app's quota (raises typed
            # QUOTA_EXCEEDED / ADMISSION_DENIED); committed to the alloc
            # id on success, rolled back on any downstream failure.
            self.qos.admit(app[0], app[1], nbytes)
        try:
            if (
                self.config.placement == "hash"
                and kind in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST)
            ):
                # Consistent-hash plan shape (control/hashring): the
                # placement is computed HERE, at the app's origin — no
                # leader round trip on the alloc path at all.
                r = self._hash_alloc(msg, kind, nbytes)
            elif not self.is_leader:
                r = self._proxy_alloc_to_leader(msg, local_app, app)
            else:
                r = self._place_alloc(msg, kind, nbytes)
        except BaseException:
            if local_app:
                self.qos.abort(app[0], app[1], nbytes)
            raise
        if local_app:
            self.qos.commit(app[0], app[1], r.fields["alloc_id"], nbytes)
        return r

    def _proxy_alloc_to_leader(self, msg: Message, local_app: bool,
                               app: tuple[int, int]) -> Message:
        """Forward REQ_ALLOC to the current leader. With leadership
        transfer armed, retryable failures — a dead leader mid-election,
        a fenced old leader's STALE_EPOCH, a NOT_MASTER redirect — are
        re-walked against the (possibly updated) leader until
        failover_wait_s elapses, so in-flight allocs converge through a
        leader change instead of surfacing the election window to the
        app. Unarmed clusters keep the single-shot PR-11 behavior."""
        deadline = time.monotonic() + (
            self.config.failover_wait_s
            if self.config.standby_masters > 0 else 0.0
        )
        last: BaseException | None = None
        while True:
            le = self._leader_entry()
            fwd = self._with_priority_tail(
                msg,
                self.qos.priority_of(*app) if local_app else None,
                le.connect_host, le.port,
            )
            try:
                return self._peer_request(le.connect_host, le.port, fwd)
            except OcmRemoteError as e:
                if e.code == int(ErrCode.NOT_MASTER) and getattr(
                    e, "leader_rank", None
                ) is not None:
                    self._adopt_leader_hint(e)
                    last = e
                elif e.code == int(ErrCode.STALE_EPOCH):
                    last = e  # fenced old leader: wait out the election
                else:
                    raise
            except (OSError, OcmConnectError) as e:
                last = e
            if time.monotonic() >= deadline:
                raise last
            time.sleep(0.05)  # let the election/LEADER_UPDATE land

    def _hash_live_ranks(self) -> list[int]:
        return sorted(
            e.rank for e in self.entries
            if e.port
            and not self.entries.has_left(e.rank)
            and not self._believed_dead(e.rank)
        )

    def _hash_alloc(self, msg: Message, kind: OcmKind,
                    nbytes: int) -> Message:
        """Origin-local placement by rendezvous hashing: mint the id
        from THIS daemon's globally-unique space, compute the chain over
        the live view, provision via DO_REPLICA (idempotent chain
        upsert — the same provisioning contract the leader uses), and
        defer the leader's capacity accounting to the reaper. A primary
        whose provision fails on transport (a just-died rank the
        detector hasn't verdicted yet) is barred and the plan recomputed
        over the shrunken set; the journaled ``hash_place`` records the
        member set actually used, which is exactly what the auditor's
        ``placement-agreement`` invariant recomputes against."""
        import json

        f = msg.fields
        data = bytes(msg.data)
        off = 0
        k = 1
        if msg.flags & FLAG_REPLICAS and len(data) > off:
            k = max(1, min(data[off], 8))
            off += 1
        if msg.flags & FLAG_QOS_TAIL and len(data) > off:
            prio = min(max(data[off], PRIO_LOW), PRIO_HIGH)
        elif f["orig_rank"] == self.rank:
            prio = self.qos.priority_of(f["pid"], f["orig_rank"])
        else:
            prio = PRIO_NORMAL
        alloc_id = self.registry.next_id()
        barred: set[int] = set()
        last: BaseException | None = None
        busy_hint = -1  # max retry hint seen; >= 0 once any rank was BUSY
        live = self._hash_live_ranks()
        for _ in range(max(1, len(live))):
            cands = [r for r in live if r not in barred]
            if not cands:
                break
            chain = hashring.plan(alloc_id, cands, k)
            try:
                confirmed, offset0 = self._provision_chain(
                    alloc_id, chain, kind, nbytes,
                    f["orig_rank"], f["pid"], prio,
                )
            except (OSError, OcmError) as e:
                # Primary unreachable OR past its watermark (typed BUSY
                # from the owner-side check, _on_do_replica): bar it and
                # re-plan over the rest — the leader path's "place on
                # the least-loaded rank below high" becomes "spill to a
                # rank that still admits". Only when EVERY candidate is
                # busy does the origin surface BUSY (below), with the
                # largest suggested backoff seen.
                hint = _busy_hint_of(e)
                if hint is not None:
                    busy_hint = max(busy_hint, hint)
                barred.add(chain[0])
                last = e
                continue
            obs_journal.record(
                "hash_place", track=self.tracer.track,
                alloc_id=alloc_id, epoch=self.entries.epoch,
                live=list(cands), k=k, chain=list(chain),
            )
            self.ldr_counters["hash_placements"] += 1
            for rr in confirmed:
                self._queue_note_alloc(kind, rr, nbytes)
            owner = self.entries[chain[0]]
            tail = (
                json.dumps({"replicas": confirmed[1:]}).encode()
                if len(confirmed) > 1 else b""
            )
            return Message(
                MsgType.ALLOC_RESULT,
                {
                    "alloc_id": alloc_id,
                    "rank": chain[0],
                    "device_index": 0,
                    "kind": WIRE_KIND[kind.value],
                    "offset": offset0,
                    "nbytes": nbytes,
                    "owner_host": owner.connect_host,
                    "owner_port": owner.port,
                },
                tail,
            )
        if busy_hint >= 0:
            # Hash-mode back-pressure (ROADMAP item 2 remaining): every
            # live rank is past the high watermark — the retryable BUSY
            # the leader path would have raised, now enforced at the
            # origin from the owners' own arena accounting. The reaper's
            # pressure eviction is busy making room; clients absorb this
            # with the standard jittered backoff.
            self.qos.note_busy()
            obs_journal.record(
                "backpressure_busy", track=self.tracer.track,
                nbytes=nbytes, pid=f["pid"], orig_rank=f["orig_rank"],
                origin="hash",
            )
            raise OcmBusy(
                f"every live rank past the high watermark "
                f"({self.config.arena_high_pct}%): retry later",
                retry_after_ms=busy_hint or self.config.busy_backoff_ms,
            )
        raise OcmPlacementError(
            f"hash placement found no reachable primary among "
            f"{live} (last: {last})"
        )

    def _provision_chain(
        self, alloc_id: int, chain: tuple[int, ...], kind: OcmKind,
        nbytes: int, orig_rank: int, pid: int, prio: int,
    ) -> tuple[list[int], int]:
        """Provision one owner chain under a pre-minted id: DO_REPLICA
        to each member, primary first. The primary must succeed (its
        failure raises and nothing is charged); a replica that fails
        just shrinks the chain (degraded, journaled), and confirmed
        members are re-sent the corrected chain so every holder agrees
        on the promotion order. Shared by the leader's replicated-alloc
        path and the origin-local hash path — one provisioning contract.
        Returns (confirmed members, primary extent offset)."""
        csv = ",".join(str(r) for r in chain)
        qflags, qtail = _priority_tail(prio)
        confirmed: list[int] = []
        offset0 = 0
        for rr in chain:
            m = Message(
                MsgType.DO_REPLICA,
                {
                    "alloc_id": alloc_id,
                    "kind": WIRE_KIND[kind.value],
                    "nbytes": nbytes,
                    "orig_rank": orig_rank,
                    "pid": pid,
                    "chain": csv,
                    "epoch": self.epoch,
                },
                qtail,
                flags=qflags,
            )
            try:
                if rr == self.rank:
                    r = self._on_do_replica(m)
                else:
                    e = self.entries[rr]
                    r = self._peer_request(e.connect_host, e.port, m)
            except (OSError, OcmError):
                if rr == chain[0]:
                    raise  # no primary, no allocation
                obs_journal.record(
                    "replica_provision_fail", track=self.tracer.track,
                    alloc_id=alloc_id, rank=rr,
                )
                printd("daemon %d: replica provision on rank %d failed",
                       self.rank, rr)
                continue
            if rr == chain[0]:
                offset0 = r.fields["offset"]
            confirmed.append(rr)
        if len(confirmed) < len(chain):
            fixed = ",".join(str(r) for r in confirmed)
            m2_fields = {
                "alloc_id": alloc_id,
                "kind": WIRE_KIND[kind.value],
                "nbytes": nbytes,
                "orig_rank": orig_rank,
                "pid": pid,
                "chain": fixed,
                "epoch": self.epoch,
            }
            for rr in confirmed:
                try:
                    if rr == self.rank:
                        self._on_do_replica(
                            Message(MsgType.DO_REPLICA, dict(m2_fields))
                        )
                    else:
                        e = self.entries[rr]
                        self._peer_request(
                            e.connect_host, e.port,
                            Message(MsgType.DO_REPLICA, dict(m2_fields)),
                        )
                except (OSError, OcmError):
                    printd("daemon %d: chain fixup on rank %d failed",
                           self.rank, rr)
        return confirmed, offset0

    def _with_priority_tail(
        self, msg: Message, priority: int | None, host: str, port: int
    ) -> Message:
        """Append the FLAG_QOS_TAIL priority u8 to a forwarded
        REQ_ALLOC — only for a non-default class, and only when the peer
        granted FLAG_CAP_QOS (default-priority traffic ships unchanged
        frames, preserving wire byte-identity and skipping the
        capability probe entirely)."""
        if (
            priority is None
            or priority == PRIO_NORMAL
            or not self._peer_caps_for(host, port) & FLAG_CAP_QOS
        ):
            return msg
        return Message(
            msg.type, msg.fields,
            bytes(msg.data) + bytes([priority]),
            msg.flags | FLAG_QOS_TAIL,
        )

    def _place_alloc(self, msg: Message, kind: OcmKind,
                     nbytes: int) -> Message:
        """Leader placement: parse the optional tails, run back-pressure,
        site the allocation, drive the DO_ALLOC/DO_REPLICA leg(s)."""
        f = msg.fields
        # Pinned by the hash-placement acceptance test: with
        # OCM_PLACEMENT=hash no REQ_ALLOC is ever placed here.
        self.ldr_counters["placements"] += 1
        # Data-tail layout after the generic trace strip:
        # [k u8 if FLAG_REPLICAS] [priority u8 if FLAG_QOS_TAIL].
        data = bytes(msg.data)
        off = 0
        k = 1
        if msg.flags & FLAG_REPLICAS and len(data) > off:
            if kind in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST):
                k = max(1, min(data[off], 8))
            off += 1
        if msg.flags & FLAG_QOS_TAIL and len(data) > off:
            prio = min(max(data[off], PRIO_LOW), PRIO_HIGH)
        elif f["orig_rank"] == self.rank:
            prio = self.qos.priority_of(f["pid"], f["orig_rank"])
        else:
            prio = PRIO_NORMAL
        # Back-pressure (host kinds): when even the least-loaded alive
        # rank is past the high watermark, answer retryable BUSY with a
        # suggested backoff instead of packing arenas to the brim — the
        # reaper's pressure eviction is busy making room. High-priority
        # apps bypass it (their work is what the room is being made for).
        if (
            kind in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST)
            and prio < PRIO_HIGH
        ):
            high = self.config.arena_high_pct / 100.0
            occ = self.policy.min_host_occupancy()
            if occ is not None and occ >= high:
                self.qos.note_busy()
                obs_journal.record(
                    "backpressure_busy", track=self.tracer.track,
                    occupancy=round(occ, 4), nbytes=nbytes,
                    pid=f["pid"], orig_rank=f["orig_rank"],
                )
                raise OcmBusy(
                    f"host arenas at {occ:.0%} (high watermark "
                    f"{self.config.arena_high_pct}%): retry later",
                    retry_after_ms=suggest_backoff_ms(
                        occ, high, self.config.busy_backoff_ms
                    ),
                )
        placed = self.policy.place(f["orig_rank"], kind, nbytes, replicas=k)
        if placed.replica_ranks:
            return self._alloc_replicated(f, placed, nbytes, priority=prio)
        owner = self.entries[placed.rank]
        if placed.rank == self.rank:
            alloc_id, offset = self._do_alloc_local(
                placed.kind, placed.device_index, nbytes, f["orig_rank"],
                f["pid"], priority=prio,
            )
        else:
            leg = Message(
                MsgType.DO_ALLOC,
                {
                    "orig_rank": f["orig_rank"],
                    "pid": f["pid"],
                    "kind": WIRE_KIND[placed.kind.value],
                    "device_index": placed.device_index,
                    "nbytes": nbytes,
                },
            )
            leg = self._with_priority_tail(
                leg, prio, owner.connect_host, owner.port
            )
            r = self._peer_request(owner.connect_host, owner.port, leg)
            alloc_id, offset = r.fields["alloc_id"], r.fields["offset"]
        self.policy.note_alloc(placed, nbytes)
        return Message(
            MsgType.ALLOC_RESULT,
            {
                "alloc_id": alloc_id,
                "rank": placed.rank,
                "device_index": placed.device_index,
                "kind": WIRE_KIND[placed.kind.value],
                "offset": offset,
                "nbytes": nbytes,
                "owner_host": owner.connect_host,
                "owner_port": owner.port,
            },
        )

    def _alloc_replicated(self, f: dict, placed, nbytes: int,
                          priority: int = PRIO_NORMAL) -> Message:
        """Provision a k-way replicated allocation (leader path): one
        alloc_id minted HERE (every daemon's id space is globally
        unique, so every chain member can register the same id), then
        the shared chain-provisioning contract (_provision_chain):
        primary must succeed, failed replicas shrink the chain, and the
        corrected chain is re-pushed so every holder agrees on the
        promotion order. Non-default priority rides every leg
        (FLAG_QOS_TAIL u8) so a promoted replica inherits the class —
        eviction discipline must survive failover."""
        import json

        chain = (placed.rank, *placed.replica_ranks)
        alloc_id = self.registry.next_id()
        confirmed, offset0 = self._provision_chain(
            alloc_id, chain, placed.kind, nbytes,
            f["orig_rank"], f["pid"], priority,
        )
        for rr in confirmed:
            self.policy.note_alloc(
                Placement(rank=rr, device_index=0, kind=placed.kind), nbytes
            )
        owner = self.entries[placed.rank]
        return Message(
            MsgType.ALLOC_RESULT,
            {
                "alloc_id": alloc_id,
                "rank": placed.rank,
                "device_index": placed.device_index,
                "kind": WIRE_KIND[placed.kind.value],
                "offset": offset0,
                "nbytes": nbytes,
                "owner_host": owner.connect_host,
                "owner_port": owner.port,
            },
            # Replica ranks ride as a JSON data tail: old clients parse
            # the fixed fields and ignore trailing data, so the reply
            # stays v2-compatible.
            json.dumps({"replicas": confirmed[1:]}).encode(),
        )

    def _on_do_replica(self, msg: Message) -> Message:
        """Provision (or chain-update) one member of a replica chain.
        Idempotent upsert: an existing entry just adopts the new chain —
        how degraded-chain fixups and re-replication chain extensions
        reach surviving holders."""
        f = msg.fields
        self._adopt_epoch(f["epoch"])
        kind = OcmKind(WIRE_KIND_INV[f["kind"]])
        if kind not in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST):
            raise OcmInvalidHandle("only host-kind allocations replicate")
        chain = tuple(_parse_owners(f["chain"]))
        try:
            existing = self.registry.lookup(f["alloc_id"])
        except OcmInvalidHandle:
            existing = None
        if existing is not None:
            self.registry.set_chain(f["alloc_id"], chain, f["epoch"])
            return Message(
                MsgType.DO_REPLICA_OK,
                {"alloc_id": f["alloc_id"],
                 "offset": existing.extent.offset},
            )
        prio = PRIO_NORMAL
        if msg.flags & FLAG_QOS_TAIL and len(msg.data) >= 1:
            prio = min(max(bytes(msg.data[:1])[0], PRIO_LOW), PRIO_HIGH)
        # Hash-mode back-pressure: with OCM_PLACEMENT=hash there is no
        # leader on the alloc path to run the watermark check, so the
        # OWNER enforces it on every fresh provision from its own arena
        # book — the one ledger that is exactly synced by construction.
        # High-priority traffic bypasses, as on the leader path; the
        # origin (_hash_alloc) spills to another rank or surfaces BUSY.
        if self.config.placement == "hash" and prio < PRIO_HIGH:
            self._check_arena_watermark(f["nbytes"])
        extent = self.host_arena.alloc(f["nbytes"])
        self.registry.insert(
            RegEntry(
                alloc_id=f["alloc_id"],
                kind=kind,
                rank=self.rank,
                device_index=0,
                extent=extent,
                nbytes=f["nbytes"],
                origin_rank=f["orig_rank"],
                origin_pid=f["pid"],
                lease_expiry=self.registry.new_lease_deadline(),
                chain=chain,
                epoch=f["epoch"],
                priority=prio,
            )
        )
        alloctrace.note_alloc(
            self._trace_scope, f["alloc_id"], f["nbytes"], kind.name
        )
        return Message(
            MsgType.DO_REPLICA_OK,
            {"alloc_id": f["alloc_id"], "offset": extent.offset},
        )

    # DO_ALLOC on the owner: reserve BEFORE replying (race fix).
    def _on_do_alloc(self, msg: Message) -> Message:
        f = msg.fields
        kind = OcmKind(WIRE_KIND_INV[f["kind"]])
        prio = PRIO_NORMAL
        if msg.flags & FLAG_QOS_TAIL and len(msg.data) >= 1:
            prio = min(max(bytes(msg.data[:1])[0], PRIO_LOW), PRIO_HIGH)
        alloc_id, offset = self._do_alloc_local(
            kind, f["device_index"], f["nbytes"], f["orig_rank"], f["pid"],
            priority=prio,
        )
        return Message(MsgType.DO_ALLOC_OK, {"alloc_id": alloc_id, "offset": offset})

    def _check_arena_watermark(self, nbytes: int) -> None:
        """Owner-side BUSY watermark (hash placement): refuse a fresh
        host-kind provision once this arena crossed the high watermark,
        with the same suggested-backoff tail the leader path ships. The
        reaper's pressure eviction brings occupancy back below low."""
        cap = self.config.host_arena_bytes
        if cap <= 0:
            return
        high = self.config.arena_high_pct / 100.0
        occ = self.host_arena.allocator.bytes_live / cap
        if occ >= high:
            raise OcmBusy(
                f"rank {self.rank} host arena at {occ:.0%} (high "
                f"watermark {self.config.arena_high_pct}%): retry later",
                retry_after_ms=suggest_backoff_ms(
                    occ, high, self.config.busy_backoff_ms
                ),
            )

    def _do_alloc_local(
        self, kind: OcmKind, device_index: int, nbytes: int, orig_rank: int,
        origin_pid: int = 0, priority: int = PRIO_NORMAL,
    ) -> tuple[int, int]:
        """alloc_ate analogue (alloc.c:151-222): reserve the extent in the
        owner's arena and register the allocation."""
        if kind in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST):
            extent = self.host_arena.alloc(nbytes)
            device_index = 0
        else:
            if not 0 <= device_index < self.ndevices:
                raise OcmInvalidHandle(f"bad device_index {device_index}")
            extent = self.device_books[device_index].alloc(nbytes)
        alloc_id = self.registry.next_id()
        self.registry.insert(
            RegEntry(
                alloc_id=alloc_id,
                kind=kind,
                rank=self.rank,
                device_index=device_index,
                extent=extent,
                nbytes=nbytes,
                origin_rank=orig_rank,
                origin_pid=origin_pid,
                lease_expiry=self.registry.new_lease_deadline(),
                priority=priority,
            )
        )
        alloctrace.note_alloc(self._trace_scope, alloc_id, nbytes, kind.name)
        return alloc_id, extent.offset

    # REQ_FREE from an app: forward to the owner (msg_send_req_free
    # analogue, mem.c:265-295) and fix the rank-0 accounting the reference
    # stubbed (mem.c:221-229).
    def _on_req_free(self, msg: Message) -> Message:
        f = msg.fields
        owner_rank = f["rank"]
        if not 0 <= owner_rank < len(self.entries):
            raise OcmInvalidHandle(f"bad owner rank {owner_rank}")
        if owner_rank == self.rank:
            self._do_free_local(f["alloc_id"])
        else:
            owner = self.entries[owner_rank]
            try:
                self._peer_request(
                    owner.connect_host, owner.port,
                    Message(MsgType.DO_FREE, {"alloc_id": f["alloc_id"]}),
                )
            except (OSError, OcmConnectError):
                # Owner unreachable mid-failover: answer RETRYABLE so
                # the client's free ladder can re-aim at a promoted
                # replica (a generic UNKNOWN here left clients of a
                # killed owner unable to release replicated handles).
                return _err(
                    ErrCode.REPLICA_UNAVAILABLE,
                    f"owner rank {owner_rank} unreachable for free of "
                    f"alloc {f['alloc_id']} (retry a replica)",
                )
        # Quota give-back at the ORIGIN daemon (idempotent: the local-
        # owner branch already released through _do_free_local).
        self.qos.release(f["alloc_id"])
        return Message(MsgType.FREE_OK, {"alloc_id": f["alloc_id"]})

    def _on_do_free(self, msg: Message) -> Message:
        self._do_free_local(msg.fields["alloc_id"])
        return Message(MsgType.FREE_OK, {"alloc_id": msg.fields["alloc_id"]})

    def _do_free_local(self, alloc_id: int) -> None:
        """dealloc_ate analogue (alloc.c:231-282)."""
        try:
            e = self.registry.remove(alloc_id)
        except OcmInvalidHandle:
            # Live-migrated away (elastic/): forward the free to the new
            # owner so a client whose handle never repointed can still
            # release — and give the ORIGIN quota back here, since the
            # migration deliberately kept it reserved.
            with self._moved_lock:
                rec = self._moved.pop(alloc_id, None)
            if rec is None:
                raise
            target = rec[0]
            if 0 <= target < len(self.entries):
                pe = self.entries[target]
                try:
                    # Not an amplification loop: the tombstone was popped
                    # from _moved above, so a bounced DO_FREE can take
                    # this branch at most once per migration record —
                    # the re-send drains state instead of regenerating it.
                    self._peer_request(
                        pe.connect_host, pe.port,
                        Message(MsgType.DO_FREE, {"alloc_id": alloc_id}),  # ocm-lint: allow[relay-cycle]
                    )
                except (OSError, OcmError):
                    printd("daemon %d: forwarded free of migrated alloc "
                           "%d to rank %d failed (lease reaper is the "
                           "backstop)", self.rank, alloc_id, target)
            self.qos.release(alloc_id)
            return
        if e.frozen:
            # The payload lives on disk, not in the arena: freeing the
            # entry deletes its frozen file (idempotent) — the one
            # legitimate way a frozen extent's bytes are destroyed.
            if self._frozen is not None:
                self._frozen.delete(f"alloc-{e.alloc_id}")
        elif e.kind in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST):
            self.host_arena.free(e.extent)
        else:
            # Scrub-at-free for device extents, BEFORE the offset returns
            # to the book (no tenant can reuse a dirty extent): the device
            # twin of the host arms' free-time scrub, done at O(1) wire
            # cost by the plane controller. Skipped unless this daemon
            # knows a plane endpoint or has relayed a device write (a
            # purely bookkeeping workload would otherwise pay a wasted
            # master round trip per free); plane-owning clients also
            # scrub at alloc, covering the sync window.
            if self.plane_addr is not None or self._device_writes_relayed:
                try:
                    self._forward_to_plane(Message(
                        MsgType.PLANE_SCRUB,
                        {
                            "alloc_id": e.alloc_id,
                            "rank": self.rank,
                            "device_index": e.device_index,
                            "ext_offset": e.extent.offset,
                            "ext_nbytes": e.nbytes,
                        },
                    ))
                except (OSError, OcmError):
                    pass
            self.device_books[e.device_index].free(e.extent)
        alloctrace.note_free(self._trace_scope, alloc_id)
        obs_journal.record(
            "free_local", track=self.tracer.track, alloc_id=alloc_id,
            nbytes=e.nbytes, origin_pid=e.origin_pid,
            origin_rank=e.origin_rank, migrating=bool(e.migrating),
        )
        if e.migrating:
            # Dropping a quarantined migration copy (stream abort): its
            # bytes were never counted at rank 0 and the tenant's quota
            # still covers the SOURCE copy — no accounting to move.
            return
        # Quota give-back when this daemon is ALSO the app's origin (the
        # reaper/eviction/reclaim paths funnel here); no-op otherwise.
        self.qos.release(alloc_id)
        # Primary of a replica chain: free the replicas too (best-effort —
        # an unreachable replica's copy falls to its own lease reaper,
        # since leases stop renewing once the app's handle is gone).
        for rr in e.replica_ranks(self.rank):
            if not 0 <= rr < len(self.entries):
                continue
            pe = self.entries[rr]
            try:
                # State-bounded, not cyclic: registry.remove() succeeded
                # above, so a replica bouncing DO_FREE back finds no
                # entry here (OcmInvalidHandle with no _moved tombstone)
                # and the chain dies after one hop.
                self._peer_request(
                    pe.connect_host, pe.port,
                    Message(MsgType.DO_FREE, {"alloc_id": e.alloc_id}),  # ocm-lint: allow[relay-cycle]
                )
            except (OSError, OcmError):
                printd("daemon %d: replica free of %d on rank %d failed "
                       "(lease reaper is the backstop)",
                       self.rank, e.alloc_id, rr)
        self._note_free_leader(e)

    def _note_free_leader(self, e: RegEntry) -> None:
        note = Message(
            MsgType.NOTE_FREE,
            {
                "kind": WIRE_KIND[e.kind.value],
                "rank": e.rank,
                "device_index": e.device_index,
                "nbytes": e.nbytes,
            },
        )
        if self.is_leader:
            self._on_note_free(note)
        else:
            le = self._leader_entry()
            try:
                self._peer_request(le.connect_host, le.port, note)
            except (OSError, OcmConnectError):
                printd("daemon %d: NOTE_FREE to the leader failed",
                       self.rank)

    def _on_note_free(self, msg: Message) -> Message:
        if self.is_leader:
            f = msg.fields
            self.policy.note_free(
                Placement(
                    rank=f["rank"],
                    device_index=f["device_index"],
                    kind=OcmKind(WIRE_KIND_INV[f["kind"]]),
                ),
                f["nbytes"],
            )
        return Message(MsgType.FREE_OK, {"alloc_id": 0})

    # -- DCN data plane: one-sided put/get into the daemon's host arena ---

    def _route_put_payload(self, msg: Message, n_data: int):
        """recv_msg data router: land a DATA_PUT payload DIRECTLY in the
        destination arena extent — the recv IS the write (no scratch hop,
        no numpy copy; on this path the daemon does zero per-byte work
        beyond the kernel's socket copy). Only a chunk that fully
        validates routes; anything questionable returns None and takes
        the copy path, where the handler raises the typed error.

        TOCTOU note: a concurrent free could recycle the extent between
        this lookup and the recv completing. The window is the same class
        the copy path already has (lookup, then write) — only wider by
        the recv — and reachable only by an app freeing or abandoning an
        allocation while actively writing it; the handler revalidates
        after the recv and answers BAD_ALLOC_ID so such a writer cannot
        treat the landing as durable."""
        f = msg.fields
        if msg.type != MsgType.DATA_PUT or n_data != f["nbytes"]:
            return None
        try:
            e = self.registry.lookup(f["alloc_id"])
            if e.kind not in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST):
                return None  # device relay needs the payload as a message
            if e.frozen:
                return None  # no arena extent yet; the handler thaws
            if (
                not e.is_primary(self.rank) or e.migrating
            ) and not msg.flags & FLAG_FANOUT:
                # Replica holder or quarantined migration copy, client
                # write: the handler may have to REJECT this (role
                # discipline) — the payload must not land in the extent
                # before that decision.
                return None
            check_bounds(
                Extent(e.extent.offset, e.nbytes), f["offset"], f["nbytes"]
            )
        except OcmError:
            return None
        view = memoryview(self.host_arena.view(e.extent))
        return view[f["offset"]:f["offset"] + n_data]

    def _believed_dead(self, rank: int) -> bool:
        """Does THIS daemon consider ``rank`` dead (its own detector
        verdict, or rank 0's broadcast adopted via mark_dead)? With
        detection disabled there is no verdict and nothing is dead."""
        return (
            self.detector is not None
            and self.detector.state(rank) == PeerState.DEAD
        )

    def _check_data_role(self, e: RegEntry, msg: Message) -> None:
        """Replica-chain role discipline for client data ops: a replica
        holder serves a CLIENT op only once it believes the primary dead
        (acting primary, pending promotion); before that, accepting a
        client write would fork the copies and a read could return bytes
        the primary has already superseded. Primary-originated fan-out
        legs (FLAG_FANOUT) always land."""
        if msg.flags & FLAG_FANOUT:
            return
        if e.migrating:
            # Quarantined migration copy (elastic/): only the source's
            # stream and mirror writes may land until the flip — serving
            # a client from half-streamed bytes would break exactness.
            raise OcmNotPrimary(
                f"rank {self.rank} holds an in-flight migration copy of "
                f"alloc {e.alloc_id}; retry"
            )
        if e.is_primary(self.rank):
            return
        if msg.type == MsgType.DATA_GET:
            # Replica holders SERVE client reads (hedged replica reads,
            # Tail-at-Scale): every acked write already landed on the
            # whole chain before its ack (the pre-ack fan-out), so a
            # replica read is exactly as fresh as the client's acked
            # state — reads cannot fork copies, only writes can, and
            # those keep the NOT_PRIMARY discipline below.
            return
        primary = e.chain[0]
        if not self._believed_dead(primary):
            raise OcmNotPrimary(
                f"rank {self.rank} holds a replica of alloc {e.alloc_id}; "
                f"primary rank {primary} is not known dead"
            )

    def _on_data_put(self, msg: Message) -> Message:
        f = msg.fields
        e = self._lookup_serving(f["alloc_id"])
        if e.kind not in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST):
            return self._relay_device_op(msg, e)
        self._check_data_role(e, msg)
        if e.frozen:
            self._thaw(e)
        if len(msg.data) != f["nbytes"]:
            raise OcmProtocolError("DATA_PUT length mismatch")
        check_bounds(Extent(e.extent.offset, e.nbytes), f["offset"], f["nbytes"])
        if not getattr(msg, "data_landed", False):
            import numpy as np

            self.host_arena.write(
                e.extent, np.frombuffer(msg.data, dtype=np.uint8),
                f["offset"],
            )
        # else: payload already recv'd straight into the arena extent by
        # _route_put_payload (which enforced the same role discipline).
        if not msg.flags & FLAG_FANOUT:
            # Outbound-migration bookkeeping (elastic/), AFTER the local
            # write so a concurrent dirty flush can never stream stale
            # bytes and still clear the marker: record the dirty range
            # for the pre-copy re-stream, or bounce retryably (write
            # landed but UNACKED) once the flip fence is up.
            self._note_migration_write(e.alloc_id, f["offset"], f["nbytes"])
            self._fan_out_put(e, f["offset"], f["nbytes"], msg.data)
            # Client-facing ack (never the fan-out legs themselves): the
            # auditor pairs this against the replica_fanout recorded
            # above — an ack with chain>1 and no prior fan-out is a
            # durability violation.
            obs_journal.record(
                "put_ack", track=self.tracer.track,
                alloc_id=e.alloc_id, offset=f["offset"],
                nbytes=f["nbytes"], chain=len(e.chain),
            )
        return Message(MsgType.DATA_PUT_OK, {"nbytes": f["nbytes"]})

    def _fan_out_put(self, e: RegEntry, offset: int, nbytes: int,
                     data) -> None:
        """Write replication: mirror an applied client DATA_PUT to every
        other chain member BEFORE acking (synchronous — a byte the
        client saw acked is on every live replica, so a promoted replica
        serves it back byte-exact). Chain members the detector holds
        DEAD are skipped (counted; re-replication repairs them). A
        member that is NOT known dead but cannot be reached fails the
        put with retryable REPLICA_UNAVAILABLE after one immediate
        retry: acking a write the chain doesn't hold would silently
        break the durability contract the client asked for. Runs on the
        primary — or on a replica acting as primary once it believes the
        primary dead (the pre-promotion window)."""
        if not e.chain:
            return
        fan0 = time.monotonic() if obs_journal.enabled() else 0.0
        try:
            self._fan_out_legs(e, offset, nbytes, data)
        finally:
            if fan0:
                # Bound to the ambient serve span (dcn_put_srv): the
                # synchronous mirror legs are the dominant slice of a
                # replicated put's server time, and critpath should name
                # them instead of lumping them into "handler".
                obs_journal.phase(
                    "replica_fanout", time.monotonic() - fan0,
                    track=self.tracer.track, chain=len(e.chain),
                )

    def _fan_out_legs(self, e: RegEntry, offset: int, nbytes: int,
                      data) -> None:
        for rr in e.chain:
            if rr == self.rank or not 0 <= rr < len(self.entries):
                continue
            if self._believed_dead(rr):
                self.res_counters["repl_put_skips"] += 1
                continue
            pe = self.entries[rr]
            leg = Message(
                MsgType.DATA_PUT,
                {"alloc_id": e.alloc_id, "offset": offset,
                 "nbytes": nbytes},
                data,
                flags=FLAG_FANOUT,
            )
            err: Exception | None = None
            for _ in range(2):  # one immediate retry (fresh connection)
                try:
                    self.peers.request(pe.connect_host, pe.port, leg)
                    err = None
                    break
                except (OSError, OcmError) as exc:
                    err = exc
            if err is None:
                continue
            self.res_counters["repl_put_errors"] += 1
            obs_journal.record(
                "replica_put_fail", track=self.tracer.track,
                alloc_id=e.alloc_id, rank=rr,
                error=f"{type(err).__name__}: {err}",
            )
            printd("daemon %d: replica put of %d to rank %d failed",
                   self.rank, e.alloc_id, rr)
            raise OcmReplicaUnavailable(
                f"replica rank {rr} unreachable for alloc {e.alloc_id} "
                f"({type(err).__name__}: {err}); retry after the "
                "detector resolves it"
            )
        if len(e.chain) > 1:
            # Every live leg landed (dead members skipped + counted):
            # recorded BEFORE the caller acks, which is exactly the
            # order the audit invariant checks.
            obs_journal.record(
                "replica_fanout", track=self.tracer.track,
                alloc_id=e.alloc_id, offset=offset, nbytes=nbytes,
                legs=sum(1 for rr in e.chain
                         if rr != self.rank and not self._believed_dead(rr)),
                skips=sum(1 for rr in e.chain
                          if rr != self.rank and self._believed_dead(rr)),
            )

    def _on_data_get(self, msg: Message) -> Message:
        f = msg.fields
        e = self._lookup_serving(f["alloc_id"])
        if e.kind not in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST):
            return self._relay_device_op(msg, e)
        self._check_data_role(e, msg)
        if e.frozen:
            # Promotion rides the existing get path: the FROZEN extent
            # is just a slow read at its owner (thaw, then serve).
            self._thaw(e)
        check_bounds(Extent(e.extent.offset, e.nbytes), f["offset"], f["nbytes"])
        # One-copy reply payload: SNAPSHOT the extent bytes at handler
        # time (a live view would keep streaming the arena for the whole
        # TCP send — a reaper-expired lease could recycle the extent
        # mid-send and leak the next tenant's bytes), but skip the old
        # tobytes + frame-concat copies via send_msg's scatter-gather.
        # The snapshot lands in a per-serve-thread REUSABLE buffer: the
        # reply is fully on the wire before this thread recvs the next
        # request, so the buffer is free again by then, and reuse avoids
        # a fresh 16 MiB allocation's page faults per chunk.
        n = f["nbytes"]
        buf = getattr(self._get_buf, "buf", None)
        if buf is None or len(buf) < n or (
            len(buf) > (32 << 20) and n < len(buf) // 4
        ):
            buf = self._get_buf.buf = bytearray(n)
        sink = memoryview(buf)[:n]
        sink[:] = memoryview(self.host_arena.view(e.extent))[
            f["offset"]:f["offset"] + n
        ]
        return Message(MsgType.DATA_GET_OK, {"nbytes": n}, sink)

    # -- shm fabric control plane (fabric/shm.py) -------------------------
    #
    # The data moved by memcpy through the shared arena segment; these
    # legs carry everything that must stay authoritative on the owner:
    # registry lookup, extent identity, bounds, replica role, epoch
    # fencing (all three types are in _FENCED_REJECT) — and the replica
    # fan-out for puts, which rides TCP exactly like a framed put's.

    def _shm_entry(self, msg: Message) -> RegEntry:
        """Shared validation for the shm control legs: the entry must be
        host-kind (device bytes live in the app plane, not this arena),
        honor replica role discipline, and — for PUT/GET — match the
        extent the client's cached mapping used (a freed-and-recycled
        extent answers BAD_ALLOC_ID, so a stale mapping can never be
        blessed) and stay in bounds."""
        f = msg.fields
        # Segment identity first: a restarted daemon on the same
        # host:port serves the SAME alloc_ids (snapshot restore) out of
        # a FRESH segment — acking a client whose memcpy landed in the
        # dead daemon's orphaned mapping would silently lose the bytes.
        # STALE_EPOCH is the failover signal: the client drops its
        # cached fabric and re-negotiates.
        served = self.fabrics.get("shm")
        if served is None or f["seg"] != served.descriptor()["seg"]:
            raise OcmRemoteError(
                int(ErrCode.STALE_EPOCH),
                f"rank {self.rank} does not serve segment {f['seg']!r} "
                "(daemon restarted?) — re-negotiate the fabric",
            )
        e = self._lookup_serving(f["alloc_id"])
        if e.kind not in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST):
            raise OcmInvalidHandle(
                "shm fabric serves host-kind allocations only"
            )
        self._check_data_role(e, msg)
        if e.frozen:
            # The client's memcpy needs a live arena extent; SHM_MAP
            # replies with the thawed offset, so stale-mapping checks
            # below always see the post-thaw extent.
            self._thaw(e)
        if "ext_offset" in f:
            if f["ext_offset"] != e.extent.offset:
                raise OcmInvalidHandle(
                    f"stale fabric mapping for alloc {f['alloc_id']}: "
                    f"mapped extent {f['ext_offset']}, live extent "
                    f"{e.extent.offset} — re-map"
                )
            check_bounds(
                Extent(e.extent.offset, e.nbytes), f["offset"], f["nbytes"]
            )
        return e

    def _on_shm_map(self, msg: Message) -> Message:
        e = self._shm_entry(msg)
        return Message(
            MsgType.SHM_MAP_OK,
            {"alloc_id": e.alloc_id, "ext_offset": e.extent.offset,
             "ext_nbytes": e.nbytes},
        )

    def _on_shm_put(self, msg: Message) -> Message:
        f = msg.fields
        e = self._shm_entry(msg)
        if not msg.flags & FLAG_FANOUT:
            # Same migration bookkeeping as a framed put: the memcpy
            # already landed in the segment (unacked if fenced).
            self._note_migration_write(e.alloc_id, f["offset"], f["nbytes"])
        self.fabric_counters["shm_puts"] += 1
        self.fabric_counters["shm_put_bytes"] += f["nbytes"]
        self.tracer.note_transfer(
            "shm_put_srv", f["nbytes"], 0.0, coalesced=False, fabric="shm",
        )
        # Replica fan-out stays on TCP: mirror the just-landed segment
        # bytes to every live chain member BEFORE acking, the same
        # durability contract as a framed put (a byte the client saw
        # acked is on every live replica). Snapshot the extent window —
        # the client may already be memcpying the next transfer.
        if e.chain and not msg.flags & FLAG_FANOUT:
            view = memoryview(self.host_arena.view(e.extent))
            data = bytes(view[f["offset"]:f["offset"] + f["nbytes"]])
            self._fan_out_put(e, f["offset"], f["nbytes"], data)
        if not msg.flags & FLAG_FANOUT:
            obs_journal.record(
                "put_ack", track=self.tracer.track,
                alloc_id=e.alloc_id, offset=f["offset"],
                nbytes=f["nbytes"], chain=len(e.chain),
            )
        return Message(MsgType.DATA_PUT_OK, {"nbytes": f["nbytes"]})

    def _on_shm_get(self, msg: Message) -> Message:
        f = msg.fields
        self._shm_entry(msg)
        self.fabric_counters["shm_gets"] += 1
        self.fabric_counters["shm_get_bytes"] += f["nbytes"]
        self.tracer.note_transfer(
            "shm_get_srv", f["nbytes"], 0.0, coalesced=False, fabric="shm",
        )
        # The ack IS the reply; the client copies from the segment after.
        return Message(MsgType.DATA_GET_OK, {"nbytes": f["nbytes"]})

    # -- cross-process device plane (PLANE_SERVE / PLANE_PUT / PLANE_GET) --
    #
    # Device bytes live in the SPMD controller's plane arena (the daemon
    # only BOOKS extents), so a plane-less process's device data op is
    # relayed to the controller's registered plane endpoint — the bridge
    # that gives C apps / second processes the full kind taxonomy the
    # reference serves cross-process (alloc.c:151-222).

    def _on_plane_serve(self, msg: Message) -> Message:
        f = msg.fields
        new_addr = (f["host"], f["port"]) if f["port"] else None  # 0=clear
        changed = new_addr != self.plane_addr
        if not changed and f.get("relay", 0):
            # Gossiped copy of what we already hold: nothing to do.
            return Message(MsgType.PLANE_SERVE_OK, {"port": f["port"]})
        self.plane_addr = new_addr
        if changed:
            printd("daemon %d: device plane %s", self.rank,
                   f"registered at {f['host']}:{f['port']}" if new_addr
                   else "deregistered")
        if not f.get("relay", 0):
            # Even an UNCHANGED client re-registration re-arms the gossip:
            # a peer daemon that restarted (losing its in-memory endpoint)
            # re-learns it on the next reaper tick; receivers that already
            # hold the endpoint no-op above, so the steady-state cost is
            # one tiny message per peer per re-registration period.
            # Fresh (de)registration from a local client: every other
            # daemon must learn it too (owner daemons relay device ops
            # there; the master is the fallback hop, so it matters MOST).
            # Push to the master inline — one dial, and a cluster whose
            # master is down is already broken — but defer the rest to
            # the reaper loop: a synchronous broadcast here would stall
            # the registering client ~30 s per unreachable peer.
            with self._plane_sync_lock:
                self._plane_unsynced = {
                    r for r in range(len(self.entries)) if r != self.rank
                }
            if not self.is_leader:
                self._sync_plane_endpoint(only_rank=self.leader_rank)
        return Message(MsgType.PLANE_SERVE_OK, {"port": f["port"]})

    def _sync_plane_endpoint(self, only_rank: int | None = None) -> None:
        """Push the current endpoint state (set or cleared) to peers that
        have not confirmed yet; called from the reaper loop (a one-shot
        best-effort send would strand the cluster if a peer was briefly
        unreachable — then 'no device plane registered' forever)."""
        addr = self.plane_addr
        host, port = addr if addr is not None else ("", 0)
        with self._plane_sync_lock:
            pending = sorted(self._plane_unsynced)
        for r in pending:
            if only_rank is not None and r != only_rank:
                continue
            e = self.entries[r]
            try:
                # relay:1 marks the leg terminal: _on_plane_serve only
                # re-arms its own gossip for relay:0 (client-originated)
                # announcements, so a relayed endpoint cannot re-trigger
                # this sender — one hop, then the type dead-ends.
                self.peers.request(
                    e.connect_host, e.port,
                    Message(MsgType.PLANE_SERVE,  # ocm-lint: allow[relay-cycle]
                            {"host": host, "port": port, "relay": 1}),
                )
                with self._plane_sync_lock:
                    self._plane_unsynced.discard(r)
            except (OSError, OcmError):
                pass  # retried on the next reaper tick

    def _relay_device_op(self, msg: Message, e) -> Message:
        f = msg.fields
        # Owner-side bounds check first: never ship an op the extent
        # cannot satisfy.
        check_bounds(Extent(e.extent.offset, e.nbytes), f["offset"], f["nbytes"])
        if msg.type == MsgType.DATA_PUT and len(msg.data) != f["nbytes"]:
            raise OcmProtocolError("DATA_PUT length mismatch")
        if msg.type == MsgType.DATA_PUT:
            self._device_writes_relayed = True
        relay = Message(
            MsgType.PLANE_PUT if msg.type == MsgType.DATA_PUT
            else MsgType.PLANE_GET,
            {
                "alloc_id": e.alloc_id,
                "rank": self.rank,
                "device_index": e.device_index,
                "ext_offset": e.extent.offset,
                "ext_nbytes": e.nbytes,
                "offset": f["offset"],
                "nbytes": f["nbytes"],
            },
            msg.data,
        )
        return self._forward_to_plane(relay)

    def _forward_to_plane(self, relay: Message) -> Message:
        addr = self.plane_addr
        try:
            if addr is not None:
                try:
                    return self.peers.request(addr[0], addr[1], relay)
                except OcmConnectError:
                    # Nothing listens there anymore (controller crashed
                    # without deregistering). Drop the stale endpoint —
                    # clients re-register live planes periodically — and
                    # fall through to the master hop / typed error.
                    self.plane_addr = None
                    addr = None
            if not self.is_leader:
                le = self._leader_entry()  # master hop: the leader
                # learns endpoints first
                return self.peers.request(le.connect_host, le.port, relay)
        except OcmRemoteError as err:
            return _err(ErrCode(err.code) if err.code in
                        ErrCode._value2member_map_ else ErrCode.UNKNOWN,
                        err.detail)
        raise OcmInvalidHandle(
            "device-kind data needs a registered plane: construct the "
            "controller's ControlPlaneClient with ici_plane= (it serves "
            "the plane automatically)"
        )

    def _on_plane_relay(self, msg: Message) -> Message:
        """Master hop for owner daemons that don't know the endpoint."""
        return self._forward_to_plane(msg)

    # -- resilience protocol (resilience/) -------------------------------

    def _on_ping(self, msg: Message) -> Message:
        """Liveness probe + epoch/incarnation gossip. A sender rank 0's
        detector holds DEAD gets STALE_EPOCH instead of PING_OK: that is
        how a merely-partitioned owner that heals learns it was declared
        dead and fences itself (probe() surfaces the verdict as the
        DeadVerdict sentinel). Revival is only ever via ADD_NODE — a fresh
        daemon process announcing itself."""
        f = msg.fields
        self._adopt_epoch(f["epoch"])
        r = f["rank"]
        det = self.detector
        if det is not None and 0 <= r < len(self.entries) and r != self.rank:
            if det.state(r) == PeerState.DEAD:
                if self.is_leader:
                    # Only the (believed) leader issues probe verdicts,
                    # and the verdict carries its authority: the prober
                    # fences itself only when (leader_epoch, epoch)
                    # outranks its own, so a deposed claimant's stale
                    # verdicts can never fence a survivor (control/).
                    return _err(
                        ErrCode.STALE_EPOCH,
                        f"rank {r} was declared dead at epoch "
                        f"{self.epoch}",
                        struct.pack("<QQ", self.leader_epoch, self.epoch),
                    )
                # Non-leaders hold ADOPTED verdicts with no authority to
                # fence; answer plainly (without resurrecting the rank —
                # revival is the leader's call via ADD_NODE).
            else:
                det.record_ok(r, f["inc"])
        return Message(
            MsgType.PING_OK,
            {"rank": self.rank, "epoch": self.epoch,
             "inc": self.incarnation},
        )

    def _on_suspect(self, msg: Message) -> Message:
        """A peer's SUSPECT report; rank 0 arbitrates with its OWN probe
        so a single partitioned reporter can never take a healthy node
        down. Only the arbiter's consecutive-failure count reaching
        dead_after produces the DEAD verdict."""
        if not self.is_leader:
            return self._not_master_err("SUSPECT_NODE")
        f = msg.fields
        self._adopt_epoch(f["epoch"])
        r = f["rank"]
        det = self.detector
        state = PeerState.ALIVE
        if det is not None and 0 <= r < len(self.entries) and r != self.rank:
            state = det.state(r)
            if state != PeerState.DEAD:
                e = self.entries[r]
                res = probe(
                    e.connect_host, e.port, self.rank, self.epoch,
                    self.incarnation,
                    timeout=self.config.probe_timeout_s,
                )
                if res is not None and not isinstance(res, DeadVerdict):
                    self._adopt_epoch(res[0])
                    det.record_ok(r, res[1])
                    state = PeerState.ALIVE
                else:
                    state = det.record_fail(r)
                    obs_journal.record(
                        "suspect_arbitrated", track=self.tracer.track,
                        rank=r, reporter=f["reporter"], state=state.name,
                    )
                    if state == PeerState.DEAD:
                        self._failover.node_dead(r)
        return Message(
            MsgType.SUSPECT_OK,
            {"epoch": self.epoch, "state": int(state)},
        )

    def _on_epoch_update(self, msg: Message) -> Message:
        """Rank 0's fencing broadcast for a DEAD verdict. The incarnation
        match means the verdict fences exactly the process it was issued
        against: a replacement daemon that rebound the same port carries
        a fresh incarnation and ignores a stale broadcast."""
        f = msg.fields
        self._adopt_epoch(f["epoch"])
        dr = f["dead_rank"]
        if dr == self.rank:
            if f["inc"] in (0, self.incarnation):
                self._fence(f["epoch"])
        elif 0 <= dr < len(self.entries):
            if self.detector is not None:
                self.detector.mark_dead(dr)
            e = self.entries[dr]
            self.peers.evict(e.connect_host, e.port)
        return Message(MsgType.EPOCH_OK, {"epoch": self.epoch})

    def _on_promote(self, msg: Message) -> Message:
        """Reconcile the dead set against local replica chains: promote
        where this rank is the first survivor, and report (JSON tail) the
        allocations this rank is now primary for that lost copies."""
        import json

        f = msg.fields
        self._adopt_epoch(f["epoch"])
        dead = {r for r in _parse_owners(f["dead_ranks"]) if r != self.rank}
        for dr in dead:
            if self.detector is not None:
                self.detector.mark_dead(dr)
            if 0 <= dr < len(self.entries):
                e = self.entries[dr]
                self.peers.evict(e.connect_host, e.port)
        # Quarantined inbound migration copies whose source just died
        # are dropped BEFORE reconciliation — a half-streamed copy must
        # never be promoted into (or repaired onto) a chain.
        self._abort_migrations(dead, f["epoch"])
        promoted, repair = self.registry.reconcile_dead(
            dead, self.rank, f["epoch"]
        )
        self.res_counters["promotions"] += len(promoted)
        for e in promoted:
            obs_journal.record(
                "failover_promote", track=self.tracer.track,
                alloc_id=e.alloc_id, chain=list(e.chain),
                epoch=f["epoch"],
            )
            printd("daemon %d promoted to primary for alloc %d (epoch %d)",
                   self.rank, e.alloc_id, f["epoch"])
        return Message(
            MsgType.PROMOTE_OK,
            {"count": len(promoted)},
            json.dumps(repair).encode() if repair else b"",
        )

    def _on_re_replicate(self, msg: Message) -> Message:
        """Restore a lost copy: provision the target (DO_REPLICA with the
        extended chain), stream this primary's bytes over DATA_PUT, then
        adopt the new chain locally and push it to the surviving
        replicas (DO_REPLICA upsert)."""
        f = msg.fields
        self._adopt_epoch(f["epoch"])
        e = self.registry.lookup(f["alloc_id"])
        if not e.is_primary(self.rank):
            raise OcmInvalidHandle(
                f"rank {self.rank} is not primary for alloc {f['alloc_id']}"
            )
        target = f["target_rank"]
        if (
            not 0 <= target < len(self.entries)
            or target == self.rank
            or target in e.chain
        ):
            raise OcmInvalidHandle(f"bad re-replication target {target}")
        base_chain = e.chain or (self.rank,)
        new_chain = (*base_chain, target)
        csv = ",".join(str(r) for r in new_chain)
        prov = {
            "alloc_id": e.alloc_id,
            "kind": WIRE_KIND[e.kind.value],
            "nbytes": e.nbytes,
            "orig_rank": e.origin_rank,
            "pid": e.origin_pid,
            "chain": csv,
            "epoch": f["epoch"],
        }
        # The restored copy must inherit the allocation's QoS class —
        # eviction discipline has to survive repair exactly as it
        # survives failover (qos/; a default-priority tail is omitted so
        # default traffic ships unchanged frames).
        qflags, qtail = _priority_tail(e.priority)
        te = self.entries[target]
        self._peer_request(
            te.connect_host, te.port,
            Message(MsgType.DO_REPLICA, prov, qtail, flags=qflags),
        )
        # Adopt the chain BEFORE streaming so concurrent client puts
        # already fan out to the target; the bulk copy then overwrites
        # (at worst) bytes the fan-out just delivered. A put landing
        # exactly between a chunk's read and its write can still be
        # shadowed — docs/RESILIENCE.md records the window.
        self.registry.set_chain(e.alloc_id, new_chain, f["epoch"])
        chunk = min(self.config.chunk_bytes, 4 << 20)
        view = memoryview(self.host_arena.view(e.extent))[: e.nbytes]
        pos = 0
        while pos < e.nbytes:
            n = min(chunk, e.nbytes - pos)
            self.peers.request(
                te.connect_host, te.port,
                Message(
                    MsgType.DATA_PUT,
                    {"alloc_id": e.alloc_id, "offset": pos, "nbytes": n},
                    bytes(view[pos:pos + n]),
                    flags=FLAG_FANOUT,
                ),
            )
            pos += n
        for rr in new_chain[1:-1]:
            if not 0 <= rr < len(self.entries):
                continue
            pe = self.entries[rr]
            try:
                self._peer_request(
                    pe.connect_host, pe.port,
                    Message(MsgType.DO_REPLICA, dict(prov)),
                )
            except (OSError, OcmError):
                printd("daemon %d: chain push to rank %d failed",
                       self.rank, rr)
        obs_journal.record(
            "rereplicated", track=self.tracer.track,
            alloc_id=e.alloc_id, target=target, chain=list(new_chain),
        )
        return Message(
            MsgType.RE_REPLICATE_OK,
            {"alloc_id": e.alloc_id, "nbytes": e.nbytes},
        )

    # -- elastic membership + live migration (elastic/) -------------------

    def _ensure_detector(self) -> FailureDetector | None:
        """Create the failure detector lazily when membership GROWS past
        one node (a solo seed daemon others join post-boot was built
        without one — len(entries) was 1 at construction)."""
        if (
            self.detector is None
            and self.config.detect
            and len(self.entries) > 1
        ):
            self.detector = FailureDetector(
                len(self.entries), self.rank,
                suspect_after=self.config.suspect_after,
                dead_after=self.config.dead_after,
            )
            for r in self.entries.left_ranks():
                self.detector.forget(r)
        return self.detector

    def _reconcile_detector(self) -> None:
        """Make the detector's watch set match the member table — called
        after any view adoption. Idempotent, so a shared in-process view
        that was already mutated by rank 0 still grows THIS daemon's
        detector."""
        det = self._ensure_detector()
        if det is None:
            return
        left = self.entries.left_ranks()
        for e in self.entries:
            if e.rank == self.rank:
                continue
            if e.rank in left:
                det.forget(e.rank)
            else:
                det.add_rank(e.rank)

    def _queue_member_sync(self, defer: tuple[int, ...] = ()) -> None:
        """Rank 0: (re)arm the member-table broadcast toward every live
        peer and push once inline; the reaper retries stragglers.
        ``defer`` skips the INLINE push only (the brand-new joiner is not
        serving yet — it gets the table in JOIN_OK and the reaper's
        retry confirms it once its accept loop runs)."""
        with self._member_sync_lock:
            self._member_unsynced = {
                e.rank for e in self.entries
                if e.rank != self.rank
                and e.port
                and not self.entries.has_left(e.rank)
            }
        self._sync_members(skip=defer)

    def _sync_members(self, skip: tuple[int, ...] = ()) -> None:
        with self._member_sync_lock:
            pending = sorted(self._member_unsynced - set(skip))
        for r in pending:
            if self.entries.has_left(r) or self._believed_dead(r):
                with self._member_sync_lock:
                    self._member_unsynced.discard(r)
                continue
            e = self.entries[r]
            try:
                self.peers.request(
                    e.connect_host, e.port,
                    Message(
                        MsgType.MEMBER_UPDATE,
                        {"epoch": self.entries.epoch},
                        self.entries.to_wire(),
                    ),
                )
                with self._member_sync_lock:
                    self._member_unsynced.discard(r)
            except (OSError, OcmError):
                pass  # retried on the next reaper tick

    def _on_req_join(self, msg: Message) -> Message:
        """Admit a fresh daemon (rank 0 only): assign the next rank —
        or the SAME rank when the address was seen before, so a joiner
        whose JOIN_OK was lost retries idempotently instead of leaking a
        half-member slot — bump the epoch, adopt it everywhere."""
        if not self.is_leader:
            return self._not_master_err("REQ_JOIN")
        f = msg.fields
        view = self.entries
        existing = view.find(f["host"], f["port"])
        rank = existing if existing is not None else len(view)
        epoch = self.bump_epoch()
        view.upsert(NodeEntry(rank, f["host"], f["port"]), epoch=epoch)
        self.policy.add_node(
            NodeResources(
                rank=rank,
                ndevices=f["ndevices"],
                device_arena_bytes=f["device_arena_bytes"],
                host_arena_bytes=f["host_arena_bytes"],
            )
        )
        det = self._ensure_detector()
        if det is not None:
            det.add_rank(rank)
            det.mark_alive(rank)
            if f["inc"]:
                det.record_ok(rank, f["inc"])
        self.ela_counters["joins"] += 1
        obs_journal.record(
            "member_join", track=self.tracer.track,
            rank=rank, host=f["host"], port=f["port"], epoch=epoch,
            rejoin=existing is not None,
        )
        printd("daemon %d: rank %d joined at %s:%d (epoch %d)",
               self.rank, rank, f["host"], f["port"], epoch)
        self._queue_member_sync(defer=(rank,))
        if self.leader_rank != 0:
            # Joiners boot believing rank 0 leads; once leadership has
            # moved, the reaper pushes them the current LEADER_UPDATE.
            with self._leader_sync_lock:
                if self._leader_update_fields is not None:
                    self._leader_unsynced.add(rank)
        if self.config.rebalance and self._rebalancer is not None:
            threading.Thread(
                target=self._rebalancer.rebalance_safe,
                kwargs={"settle_s": self.config.heartbeat_s},
                daemon=True, name=f"ocm-rebalance-e{epoch}",
            ).start()
        return Message(
            MsgType.JOIN_OK,
            {"rank": rank, "epoch": epoch, "nnodes": self.policy.nnodes},
            view.to_wire(),
        )

    def _on_req_leave(self, msg: Message) -> Message:
        """Graceful departure (rank 0 only): migrate everything off the
        leaver, THEN bump the epoch and drop it from the view. A drain
        that cannot complete fails the leave — the member stays, because
        departing with data aboard is just a slow crash (the unclean
        path is simply dying, which the DEAD-verdict failover handles)."""
        if not self.is_leader:
            return self._not_master_err("REQ_LEAVE")
        f = msg.fields
        rank = f["rank"]
        view = self.entries
        if rank == self.rank:
            # The serving leader cannot drain itself mid-coordination;
            # the clean path is a voluntary handoff FIRST (closing the
            # "rank 0 cannot leave" hole noted in PR 8), then an
            # ordinary member departure via the successor.
            raise OcmInvalidHandle(
                f"rank {rank} is the serving leader and cannot leave — "
                "hand off leadership first (handoff_leadership)"
            )
        if not 0 <= rank < len(view) or view.has_left(rank):
            raise OcmInvalidHandle(f"rank {rank} is not a member")
        det = self.detector
        if f["inc"] and det is not None:
            known = det.incarnation(rank)
            if known and known != f["inc"]:
                raise OcmRemoteError(
                    int(ErrCode.STALE_EPOCH),
                    f"REQ_LEAVE incarnation {f['inc']:#x} does not match "
                    f"the serving daemon at rank {rank} ({known:#x})",
                )
        # Fence NEW placements off the leaver before moving data, else
        # the drain chases a moving target.
        self.policy.mark_dead(rank)
        try:
            moved, remaining = (
                self._rebalancer.drain(rank)
                if self._rebalancer is not None else (0, 0)
            )
            if remaining:
                raise OcmError(
                    f"drain of rank {rank} incomplete: {remaining} extents "
                    "still held — leave refused, member retained"
                )
        except BaseException:
            self.policy.mark_alive(rank)  # leave failed: still a member
            raise
        epoch = self.bump_epoch()
        view.mark_left(rank, epoch=epoch)
        self.policy.remove_node(rank)
        if det is not None:
            det.forget(rank)
        de = view[rank]
        self.peers.evict(de.connect_host, de.port)
        self.ela_counters["leaves"] += 1
        obs_journal.record(
            "member_leave", track=self.tracer.track,
            rank=rank, epoch=epoch, moved=moved,
        )
        printd("daemon 0: rank %d left (epoch %d, %d extents moved)",
               rank, epoch, moved)
        self._queue_member_sync()
        return Message(MsgType.LEAVE_OK, {"epoch": epoch, "moved": moved})

    def _on_member_update(self, msg: Message) -> Message:
        """Adopt rank 0's member-table broadcast (epoch-fenced: stale
        tables are dropped by ClusterView.adopt)."""
        f = msg.fields
        self._adopt_epoch(f["epoch"])
        if msg.data:
            self.entries.adopt(f["epoch"], bytes(msg.data))
        self._reconcile_detector()
        return Message(MsgType.MEMBER_OK, {"epoch": self.epoch})

    def _lookup_serving(self, alloc_id: int) -> RegEntry:
        """Registry lookup for data ops: a live-migrated id answers the
        typed MOVED redirect (new owner rank rides the error tail)
        instead of BAD_ALLOC_ID, so clients repoint instead of failing."""
        try:
            return self.registry.lookup(alloc_id)
        except OcmInvalidHandle:
            with self._moved_lock:
                rec = self._moved.get(alloc_id)
            if rec is not None:
                raise OcmMoved(
                    f"alloc {alloc_id} was migrated to rank {rec[0]}",
                    rec[0],
                ) from None
            raise

    def _note_moved(self, alloc_id: int, target: int, origin_pid: int,
                    origin_rank: int) -> None:
        with self._moved_lock:
            self._moved[alloc_id] = (
                target, origin_pid, origin_rank, time.monotonic()
            )

    def _prune_tombstones(self) -> None:
        """Drop forwarding tombstones whose app went heartbeat-stale —
        a live app's beats keep refreshing the stamp (and by then its
        client has long repointed via MOVED/REQ_LOCATE)."""
        horizon = self.config.app_stale_leases * self.config.lease_s
        now = time.monotonic()
        with self._moved_lock:
            stale = [
                a for a, rec in self._moved.items()
                if now - rec[3] > horizon
            ]
            for a in stale:
                del self._moved[a]

    def _note_migration_write(self, alloc_id: int, offset: int,
                              nbytes: int) -> None:
        """Client-write hook while THIS daemon streams the allocation
        out: record the dirty range for the pre-copy passes, or — once
        the flip fence is up — refuse retryably so the ladder re-lands
        the write on the new primary."""
        with self._mig_lock:
            st = self._migrations.get(alloc_id)
            if st is None:
                return
            if st["fence"]:
                raise OcmNotPrimary(
                    f"alloc {alloc_id} is mid-migration flip on rank "
                    f"{self.rank}; retry"
                )
            st["dirty"].append((offset, nbytes))

    def _on_migrate(self, msg: Message) -> Message:
        """Move one allocation to ``target_rank`` with zero acked-write
        loss (the source stays primary throughout the copy):

        1. provision — MIGRATE_BEGIN registers a QUARANTINED copy on the
           target (refuses client ops; dropped if this daemon dies).
        2. stream — local-only chain adoption makes every racing client
           put fan out to the target, then the extent streams over
           FLAG_FANOUT chunks; dirty ranges written mid-pass re-stream
           (bounded pre-copy), the residue flushes under a brief fence
           that bounces writers NOT_PRIMARY (retryable).
        3. flip — the target (then every surviving replica) adopts the
           chain with the target primary and the source gone.
        4. drop-source — the local entry dies; a forwarding tombstone
           answers MOVED so stale handles repoint.

        Every other holder keeps the OLD chain until the flip, so a
        source death mid-stream promotes among FULL copies only and the
        target's quarantined partial is aborted — a chain can never
        fork onto half-streamed bytes."""
        f = msg.fields
        self._adopt_epoch(f["epoch"])
        e = self._lookup_serving(f["alloc_id"])
        if e.kind not in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST):
            raise OcmInvalidHandle("only host-kind allocations migrate")
        if e.frozen:
            # Migration streams from the arena: thaw first (the target
            # receives a plain live copy — FROZEN is owner-local state).
            self._thaw(e)
        if not e.is_primary(self.rank):
            raise OcmInvalidHandle(
                f"rank {self.rank} is not primary for alloc {f['alloc_id']}"
            )
        if f["epoch"] < e.epoch:
            raise OcmRemoteError(
                int(ErrCode.STALE_EPOCH),
                f"migration epoch {f['epoch']} predates chain epoch "
                f"{e.epoch} for alloc {f['alloc_id']}",
            )
        target = f["target_rank"]
        if (
            not 0 <= target < len(self.entries)
            or target == self.rank
            or target in e.chain
            or self.entries.has_left(target)
        ):
            raise OcmInvalidHandle(f"bad migration target {target}")
        orig_chain = e.chain
        stream_chain = (*(orig_chain or (self.rank,)), target)
        epoch = max(e.epoch, f["epoch"])
        self.ela_counters["migrations_started"] += 1
        obs_journal.record(
            "migrate_start", track=self.tracer.track,
            alloc_id=e.alloc_id, src=self.rank, target=target,
            nbytes=e.nbytes, epoch=epoch,
        )
        te = self.entries[target]
        qflags, qtail = _priority_tail(e.priority)
        begin = Message(
            MsgType.MIGRATE_BEGIN,
            {
                "alloc_id": e.alloc_id,
                "kind": WIRE_KIND[e.kind.value],
                "nbytes": e.nbytes,
                "orig_rank": e.origin_rank,
                "pid": e.origin_pid,
                "chain": ",".join(str(r) for r in stream_chain),
                "src_rank": self.rank,
                "epoch": epoch,
            },
            qtail,
            flags=qflags,
        )
        try:
            self._peer_request(te.connect_host, te.port, begin)
        except (OSError, OcmError) as exc:
            self._migrate_abort(e.alloc_id, target, "provision", exc)
            raise
        with self._mig_lock:
            self._migrations[e.alloc_id] = {"dirty": [], "fence": False}
        try:
            # Local-only chain adoption: racing puts now fan out to the
            # target too; every OTHER holder keeps the old chain.
            self.registry.set_chain(e.alloc_id, stream_chain, epoch)
            self._migrate_stream(e, te, 0, e.nbytes)
            # Bounded pre-copy: re-stream ranges dirtied mid-pass.
            st = self._migrations[e.alloc_id]
            for _ in range(8):
                with self._mig_lock:
                    dirty, st["dirty"] = st["dirty"], []
                if not dirty:
                    break
                for off, n in dirty:
                    self._migrate_stream(e, te, off, n)
            # Fence the residue: late writers bounce retryable and land
            # on the target after the flip.
            with self._mig_lock:
                st["fence"] = True
                dirty = list(st["dirty"])
            for off, n in dirty:
                self._migrate_stream(e, te, off, n)
            # Flip: the target must adopt primaryship; survivors follow.
            new_chain = (
                target, *[r for r in orig_chain if r != self.rank]
            )
            flip = {
                "alloc_id": e.alloc_id,
                "kind": WIRE_KIND[e.kind.value],
                "nbytes": e.nbytes,
                "orig_rank": e.origin_rank,
                "pid": e.origin_pid,
                "chain": ",".join(str(r) for r in new_chain),
                "epoch": epoch,
            }
            self._peer_request(
                te.connect_host, te.port,
                Message(MsgType.DO_REPLICA, dict(flip)),
            )
        except (OSError, OcmError) as exc:
            # Abort: the source stays the (sole) primary under its
            # ORIGINAL chain; the target's quarantined copy is dropped
            # best-effort (its quarantine also dies with us).
            try:
                self.registry.set_chain(e.alloc_id, orig_chain, epoch)
            except OcmInvalidHandle:
                pass  # freed underneath us: nothing to restore
            with self._mig_lock:
                self._migrations.pop(e.alloc_id, None)
            try:
                self.peers.request(
                    te.connect_host, te.port,
                    Message(MsgType.DO_FREE, {"alloc_id": e.alloc_id}),
                )
            except (OSError, OcmError):
                pass
            self._migrate_abort(e.alloc_id, target, "stream", exc)
            raise
        for rr in new_chain[1:]:
            if rr == self.rank or not 0 <= rr < len(self.entries):
                continue
            pe = self.entries[rr]
            try:
                self._peer_request(
                    pe.connect_host, pe.port,
                    Message(MsgType.DO_REPLICA, dict(flip)),
                )
            except (OSError, OcmError):
                printd("daemon %d: migrate chain push to rank %d failed",
                       self.rank, rr)
        # Drop-source + tombstone. Deliberately NOT _do_free_local: the
        # tenant's quota stays reserved at its origin ledger (the bytes
        # still exist — they just moved), and placement accounting moves
        # atomically for both ends at the rank-0 rebalancer. The
        # tombstone lands BEFORE the registry entry dies so a racing
        # data op always sees either the live entry or the MOVED
        # redirect — never a bare BAD_ALLOC_ID window.
        self._note_moved(e.alloc_id, target, e.origin_pid, e.origin_rank)
        e2 = self.registry.remove(e.alloc_id)
        with self._mig_lock:
            self._migrations.pop(e.alloc_id, None)
        self.host_arena.free(e2.extent)
        alloctrace.note_free(self._trace_scope, e.alloc_id)
        self.ela_counters["migrations_completed"] += 1
        self.ela_counters["migration_bytes"] += e2.nbytes
        obs_journal.record(
            "migrate_flip", track=self.tracer.track,
            alloc_id=e.alloc_id, src=self.rank, target=target,
            nbytes=e2.nbytes, chain=list(new_chain), epoch=epoch,
        )
        printd("daemon %d: alloc %d migrated to rank %d (%d B)",
               self.rank, e.alloc_id, target, e2.nbytes)
        return Message(
            MsgType.MIGRATE_OK,
            {"alloc_id": e.alloc_id, "nbytes": e2.nbytes},
        )

    def _migrate_stream(self, e: RegEntry, te: NodeEntry, offset: int,
                        nbytes: int) -> None:
        """Stream [offset, offset+nbytes) of the extent to the target as
        FLAG_FANOUT chunks (idempotent absolute-offset writes)."""
        chunk = min(self.config.migrate_chunk_bytes, self.config.chunk_bytes)
        end = min(offset + nbytes, e.nbytes)
        view = memoryview(self.host_arena.view(e.extent))
        pos = offset
        while pos < end:
            n = min(chunk, end - pos)
            self.peers.request(
                te.connect_host, te.port,
                Message(
                    MsgType.DATA_PUT,
                    {"alloc_id": e.alloc_id, "offset": pos, "nbytes": n},
                    bytes(view[pos:pos + n]),
                    flags=FLAG_FANOUT,
                ),
            )
            pos += n

    def _migrate_abort(self, alloc_id: int, target: int, stage: str,
                       exc: BaseException) -> None:
        self.ela_counters["migrations_aborted"] += 1
        obs_journal.record(
            "migrate_abort", track=self.tracer.track,
            alloc_id=alloc_id, src=self.rank, target=target, stage=stage,
            error=f"{type(exc).__name__}: {exc}",
        )
        printd("daemon %d: migration of %d to rank %d aborted at %s: %s",
               self.rank, alloc_id, target, stage, exc)

    def _on_migrate_begin(self, msg: Message) -> Message:
        """Target side of a migration: provision (or re-adopt) the copy
        QUARANTINED — only FLAG_FANOUT stream/mirror writes land until
        the flip's chain rewrite, and the copy is dropped (never
        promoted) if the source dies mid-stream."""
        f = msg.fields
        self._adopt_epoch(f["epoch"])
        kind = OcmKind(WIRE_KIND_INV[f["kind"]])
        if kind not in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST):
            raise OcmInvalidHandle("only host-kind allocations migrate")
        chain = tuple(_parse_owners(f["chain"]))
        prio = PRIO_NORMAL
        if msg.flags & FLAG_QOS_TAIL and len(msg.data) >= 1:
            prio = min(max(bytes(msg.data[:1])[0], PRIO_LOW), PRIO_HIGH)
        try:
            existing = self.registry.lookup(f["alloc_id"])
        except OcmInvalidHandle:
            existing = None
        if existing is not None:
            if f["epoch"] < existing.epoch:
                raise OcmRemoteError(
                    int(ErrCode.STALE_EPOCH),
                    f"MIGRATE_BEGIN epoch {f['epoch']} predates chain "
                    f"epoch {existing.epoch}",
                )
            self.registry.mark_migrating(
                f["alloc_id"], chain, f["epoch"], f["src_rank"]
            )
            return Message(
                MsgType.DO_REPLICA_OK,
                {"alloc_id": f["alloc_id"],
                 "offset": existing.extent.offset},
            )
        extent = self.host_arena.alloc(f["nbytes"])
        self.registry.insert(
            RegEntry(
                alloc_id=f["alloc_id"],
                kind=kind,
                rank=self.rank,
                device_index=0,
                extent=extent,
                nbytes=f["nbytes"],
                origin_rank=f["orig_rank"],
                origin_pid=f["pid"],
                lease_expiry=self.registry.new_lease_deadline(),
                chain=chain,
                epoch=f["epoch"],
                priority=prio,
                migrating=True,
                migrate_src=f["src_rank"],
            )
        )
        # This rank holds the allocation again: any old forwarding
        # tombstone (migrated away and now coming back) is obsolete.
        with self._moved_lock:
            self._moved.pop(f["alloc_id"], None)
        alloctrace.note_alloc(
            self._trace_scope, f["alloc_id"], f["nbytes"], kind.name
        )
        return Message(
            MsgType.DO_REPLICA_OK,
            {"alloc_id": f["alloc_id"], "offset": extent.offset},
        )

    def _abort_migrations(self, dead: set[int], epoch: int) -> None:
        """Drop quarantined inbound copies whose SOURCE died mid-stream
        (called before reconcile_dead wherever a dead set lands): a
        half-streamed copy must never be promoted or repaired into a
        chain. Outbound migrations simply fail their stream and abort
        at the source's own state machine."""
        for e in self.registry.abort_migrations(dead):
            self.host_arena.free(e.extent)
            alloctrace.note_free(self._trace_scope, e.alloc_id)
            self.ela_counters["migrations_aborted"] += 1
            obs_journal.record(
                "migrate_abort", track=self.tracer.track,
                alloc_id=e.alloc_id, src=e.migrate_src, target=self.rank,
                stage="source-died", epoch=epoch,
            )
            printd("daemon %d: dropped quarantined migration copy %d "
                   "(source rank %d died)", self.rank, e.alloc_id,
                   e.migrate_src)

    def _extent_rows(self) -> list[dict]:
        """Host-kind inventory for the rebalancer (REQ_EXTENTS)."""
        rows = []
        for e in self.registry.snapshot():
            if e.kind not in (OcmKind.REMOTE_HOST, OcmKind.LOCAL_HOST):
                continue
            rows.append({
                "id": e.alloc_id,
                "kind": WIRE_KIND[e.kind.value],
                "nbytes": e.nbytes,
                "chain": list(e.chain),
                "primary": e.is_primary(self.rank),
                "prio": e.priority,
                "origin_rank": e.origin_rank,
                "origin_pid": e.origin_pid,
                "migrating": e.migrating,
            })
        rows.sort(key=lambda r: r["id"])
        return rows

    def _on_req_extents(self, msg: Message) -> Message:
        import json

        rows = self._extent_rows()
        return Message(
            MsgType.EXTENTS_OK,
            {"rank": self.rank, "count": len(rows)},
            json.dumps(rows, separators=(",", ":")).encode(),
        )

    def _on_req_locate(self, msg: Message) -> Message:
        """Where does this allocation live NOW? Answered from the local
        registry (chain head) or the forwarding tombstones — at rank 0
        the rebalancer records every flip, so this is the client
        ladder's backstop once a migration source departed entirely."""
        aid = msg.fields["alloc_id"]
        rank = None
        chain: tuple[int, ...] = ()
        try:
            e = self.registry.lookup(aid)
            rank = e.chain[0] if e.chain else self.rank
            chain = e.chain
        except OcmInvalidHandle:
            with self._moved_lock:
                rec = self._moved.get(aid)
            if rec is not None:
                rank = rec[0]
        if rank is None or not 0 <= rank < len(self.entries):
            raise OcmInvalidHandle(f"unknown alloc_id {aid}")
        e2 = self.entries[rank]
        return Message(
            MsgType.LOCATE_OK,
            {
                "alloc_id": aid,
                "rank": rank,
                "host": e2.connect_host,
                "port": e2.port,
                "chain": ",".join(str(r) for r in chain),
            },
        )

    def _elastic_meta(self) -> dict:
        """Membership/migration state for STATUS, STATUS_PROM and the
        obs cluster table."""
        return {
            "members": self.entries.alive_count(),
            "left": sorted(self.entries.left_ranks()),
            "view_epoch": self.entries.epoch,
            "counters": dict(self.ela_counters),
            "tombstones": len(self._moved),
        }

    # -- liveness --------------------------------------------------------

    def _on_heartbeat(self, msg: Message) -> Message:
        """Renew leases locally; a heartbeat arriving from a *local* app
        (origin rank == ours) is relayed to every peer daemon, since owners
        hold the leases. Relayed copies have origin rank != receiver rank,
        so they are not re-relayed (no forwarding loop)."""
        f = msg.fields
        self.registry.renew_leases(f["pid"], f["rank"])
        self.qos.touch(f["pid"], f["rank"])
        obs_journal.record(
            "lease_renew", track=self.tracer.track,
            app_pid=f["pid"], app_rank=f["rank"],
            relayed=f["rank"] != self.rank,
        )
        if msg.flags & FLAG_HB_FWD:
            # A tombstone-forwarded beat is TERMINAL: renew (done above)
            # and stop. Re-relaying it would loop — the origin's relay
            # branch fires on f["rank"] == its own rank no matter how
            # the beat got there, and two swapped migrations would
            # ping-pong a forward between their sources forever.
            return Message(
                MsgType.HEARTBEAT_OK, {"lease_s": self.registry.lease_s}
            )
        relayed_to: set[int] = set()
        if f["rank"] == self.rank:
            # Relay only to the ranks the app says own its allocations —
            # O(owners) per beat, not an O(nnodes) broadcast per app.
            for r in _parse_owners(f.get("owners", "")):
                if r == self.rank or not 0 <= r < len(self.entries):
                    continue
                relayed_to.add(r)
                e = self.entries[r]
                try:
                    self._peer_request(e.connect_host, e.port, msg)
                except (OSError, OcmConnectError):
                    printd("daemon %d: heartbeat relay to %d failed",
                           self.rank, e.rank)
        # Forward the beat along live-migration tombstones (elastic/):
        # until the app's client repoints its handle, its owners list
        # still names THIS rank — the migrated copy's lease would lapse
        # without the forward. Touching the stamp keeps the tombstone
        # alive exactly as long as the app is. Never toward the app's
        # ORIGIN rank (it renews from the app's direct beats), and the
        # forward is flagged so the receiver cannot relay it onward.
        fwd: set[int] = set()
        now = time.monotonic()
        with self._moved_lock:
            for aid, rec in self._moved.items():
                if (rec[1], rec[2]) == (f["pid"], f["rank"]):
                    self._moved[aid] = (rec[0], rec[1], rec[2], now)
                    fwd.add(rec[0])
        for r in fwd - relayed_to - {self.rank, f["rank"]}:
            if not 0 <= r < len(self.entries):
                continue
            e = self.entries[r]
            try:
                self._peer_request(
                    e.connect_host, e.port,
                    Message(MsgType.HEARTBEAT, dict(f), flags=FLAG_HB_FWD),
                )
            except (OSError, OcmConnectError):
                printd("daemon %d: migrated-lease heartbeat forward to %d "
                       "failed", self.rank, r)
        return Message(MsgType.HEARTBEAT_OK, {"lease_s": self.registry.lease_s})

    def _on_status(self, msg: Message) -> Message:
        import json

        # Data-plane telemetry + lease health ride as a JSON data tail:
        # v2 clients parse the fixed fields and ignore trailing data, so
        # the schema needs no new wire fields (the C++ daemon simply
        # sends no tail).
        detail = {
            "dcn": {
                "ops": {
                    k: v for k, v in self.tracer.snapshot().items()
                    if k.startswith("dcn_")
                },
                "transfers": self.tracer.transfers(last=32),
            },
            "leases": self.registry.lease_stats(),
            "resilience": self._resilience_meta(),
            "qos": self._qos_meta(),
            "fabric": self._fabric_meta(),
            "elastic": self._elastic_meta(),
            "mux": self._mux_meta(),
            "timebudget": dict(self.tb_counters),
            "frozen": self._frozen_meta(),
            # Arena capacities (control/): what a promoted leader's
            # whole-resync reads to rebuild placement accounting from
            # the survivors' own numbers.
            "serving": self._serving_meta(),
            "caps": {
                "ndevices": self.ndevices,
                "device_arena_bytes": self.config.device_arena_bytes,
                "host_arena_bytes": self.config.host_arena_bytes,
            },
        }
        return Message(
            MsgType.STATUS_OK,
            {
                "rank": self.rank,
                "nnodes": self.policy.nnodes if self.is_leader
                else len(self.entries),
                "live_allocs": self.registry.live_count(),
                "host_bytes_live": self.host_arena.allocator.bytes_live,
                "device_bytes_live": sum(
                    b.bytes_live for b in self.device_books
                ),
            },
            json.dumps(detail, separators=(",", ":")).encode(),
        )

    def _resilience_meta(self) -> dict:
        """Epoch/fencing/peer-state/failover counters for STATUS and the
        Prometheus exposition."""
        return {
            "epoch": self.epoch,
            "fenced": self._fenced,
            "peers": self.detector.states() if self.detector else {},
            "failover": dict(self.res_counters),
            # Leadership (control/): who coordinates, since when, and
            # how this daemon got (or observed) the role.
            "leader": self.leader_rank,
            "leader_epoch": self.leader_epoch,
            "is_leader": self.is_leader,
            "leadership": dict(self.ldr_counters),
        }

    def _qos_meta(self) -> dict:
        """Tenant/quota/eviction state for STATUS, STATUS_PROM and the
        obs cluster table's per-app rows."""
        meta = self.qos.metrics()
        scores = getattr(self.policy, "load_scores", None)
        if self.is_leader and scores is not None:
            meta["load_scores"] = scores()
        return meta

    def _fabric_meta(self) -> dict:
        """Which fabrics this daemon serves + per-fabric transfer
        counters, for STATUS and the ocm_fabric_* prom families."""
        return {
            "served": sorted(self.fabrics),
            "counters": dict(self.fabric_counters),
        }

    def _frozen_meta(self) -> dict | None:
        """FROZEN-tier counters + live occupancy for STATUS and the
        ocm_frozen_* prom families. None (omitted by render) when the
        tier is off — the STATUS tail is then byte-identical to the
        pre-persist daemon's."""
        if self._frozen is None:
            return None
        return {
            **self.frz_counters,
            "lost": len(self._frozen.lost),
            "bytes": self._frozen.bytes_stored,
            "extents": len(self._frozen.keys()),
            "max_bytes": self._frozen.max_bytes,
        }

    def _serving_meta(self) -> dict | None:
        """Co-located serving-engine stats (serving/metrics.py): an
        engine in THIS process publishes its counters and the daemon
        folds them into STATUS / STATUS_PROM — the in-band, no-new-
        MsgType observability discipline. None (omitted by render) when
        no engine lives here. The import is stdlib-only by the metrics
        module's contract."""
        from oncilla_tpu.serving import metrics as serving_metrics

        return serving_metrics.colocated()

    def _metrics_meta(self) -> dict:
        """Everything the Prometheus endpoint and the cluster CLI render:
        op counters, the transfer ring, arena occupancy, lease health."""
        return {
            "rank": self.rank,
            "nnodes": self.policy.nnodes if self.is_leader
            else len(self.entries),
            "ops": self.tracer.snapshot(),
            "transfers": self.tracer.transfers(last=32),
            "live_allocs": self.registry.live_count(),
            "host_arena": {
                "live_bytes": self.host_arena.allocator.bytes_live,
                "capacity_bytes": self.config.host_arena_bytes,
            },
            "device_books": [
                {
                    "live_bytes": b.bytes_live,
                    "capacity_bytes": self.config.device_arena_bytes,
                }
                for b in self.device_books
            ],
            "leases": self.registry.lease_stats(),
            "resilience": self._resilience_meta(),
            "qos": self._qos_meta(),
            "fabric": self._fabric_meta(),
            "elastic": self._elastic_meta(),
            "mux": self._mux_meta(),
            "timebudget": dict(self.tb_counters),
            "frozen": self._frozen_meta(),
            "serving": self._serving_meta(),
        }

    def _on_status_prom(self, msg: Message) -> Message:
        from oncilla_tpu.obs import prom

        text = prom.render(self._metrics_meta())
        return Message(
            MsgType.STATUS_PROM_OK, {"rank": self.rank}, text.encode()
        )

    def _on_status_events(self, msg: Message) -> Message:
        evs = obs_journal.events()
        return Message(
            MsgType.STATUS_EVENTS_OK,
            {"rank": self.rank, "count": len(evs)},
            obs_journal.dump_jsonl(evs).encode(),
        )


def _err(code: ErrCode, detail: str, data: bytes = b"") -> Message:
    return Message(MsgType.ERROR, {"code": int(code), "detail": detail}, data)


def _busy_hint_of(e: BaseException) -> int | None:
    """The retry hint of a BUSY-shaped error (a local OcmBusy from this
    process's own provisioning leg, or the typed wire rejection from a
    peer owner), else None."""
    if isinstance(e, OcmBusy):
        return e.retry_after_ms
    if isinstance(e, OcmRemoteError) and e.code == int(ErrCode.BUSY):
        return getattr(e, "retry_after_ms", 0)
    return None


def _priority_tail(priority: int) -> tuple[int, bytes]:
    """(flags, data tail) carrying a NON-default QoS priority on a
    provision leg (DO_REPLICA / MIGRATE_BEGIN); default-class traffic
    ships unchanged frames so the unreplicated wire stays byte-exact."""
    if priority == PRIO_NORMAL:
        return 0, b""
    return FLAG_QOS_TAIL, bytes([priority])


def _parse_owners(s: str) -> list[int]:
    """Comma-separated rank list from the wire ("1,3" -> [1, 3])."""
    out = []
    for part in s.split(","):
        part = part.strip()
        if part:
            try:
                out.append(int(part))
            except ValueError:
                continue
    return out


def main(argv=None) -> int:
    """``python -m oncilla_tpu.runtime.daemon <nodefile> [--rank N]`` — the
    per-node daemon process (``bin/oncillamem nodefile`` analogue,
    /root/reference/src/main.c:187-221, minus the busy-spin: we block on a
    signal-interruptible event)."""
    import argparse
    import signal

    from oncilla_tpu.runtime.membership import detect_rank, parse_nodefile
    from oncilla_tpu.utils.platform import honor_cpu_env

    honor_cpu_env()  # JAX_PLATFORMS=cpu must stick (see utils/platform.py)

    ap = argparse.ArgumentParser(description="oncilla-tpu daemon")
    ap.add_argument("nodefile")
    ap.add_argument("--rank", type=int, default=None)
    ap.add_argument("--policy", default="capacity", choices=sorted(POLICIES))
    ap.add_argument("--ndevices", type=int, default=1)
    ap.add_argument("--snapshot", default=None,
                    help="snapshot file: restored on start, written on stop")
    ap.add_argument("--host-arena-bytes", type=int, default=None,
                    help="served DRAM arena size (native daemon parity)")
    ap.add_argument("--device-arena-bytes", type=int, default=None,
                    help="booked per-device HBM size (native daemon parity)")
    args = ap.parse_args(argv)

    entries = parse_nodefile(args.nodefile)
    rank = args.rank if args.rank is not None else detect_rank(entries)
    cfg_kw = {}
    if args.host_arena_bytes is not None:
        cfg_kw["host_arena_bytes"] = args.host_arena_bytes
    if args.device_arena_bytes is not None:
        cfg_kw["device_arena_bytes"] = args.device_arena_bytes
    d = Daemon(rank, entries, policy=args.policy, ndevices=args.ndevices,
               host=entries[rank].host, snapshot_path=args.snapshot,
               config=OcmConfig(**cfg_kw) if cfg_kw else None)
    d.start()
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    print(f"oncilla daemon rank={rank} listening on "
          f"{entries[rank].host}:{d.port}", flush=True)
    stop.wait()
    d.stop()
    return 0


# Flag bits the daemon acts on, per request type. The protocol
# exhaustiveness gate (analysis/project.py) checks every bit declared in
# protocol.VALID_FLAGS for a request type appears here — a flag added to
# the wire without daemon support fails lint instead of silently
# degrading to lockstep (or worse, desyncing the reply stream) under
# load. CONNECT's capability offer is handled in _on_connect (echo of
# the implemented subset); DATA_PUT's FLAG_MORE in _serve_conn's burst
# loop.
# FLAG_TRACE_CTX is handled GENERICALLY in _serve_conn (the context
# prefix is stripped and installed around dispatch before any handler
# runs), so every traced request type claims it here.
_FLAGS_HANDLED = {
    # FLAG_CAP_QOS / FLAG_QOS_TAIL: QoS profile declaration parsed in
    # _on_connect; priority tails parsed in _place_alloc / _on_do_alloc /
    # _on_do_replica (qos/). FLAG_CAP_MUX: granted in _on_connect (gated
    # on config.mux_serve). FLAG_MUX_TAG: the u32 correlation id is
    # stripped GENERICALLY in _serve_conn (before the trace prefix) and
    # echoed on the reply — the same generic-strip discipline as
    # FLAG_TRACE_CTX, so it appears on every client-facing request type.
    # FLAG_CAP_DEADLINE: granted in _on_connect; FLAG_DEADLINE (the u32
    # remaining-budget prefix) is stripped GENERICALLY in _serve_conn —
    # the FLAG_TRACE_CTX discipline — re-anchored on this host's clock,
    # refused typed when expired (before any handler side effect), and
    # re-attached decremented on forwarded hops via _peer_request.
    MsgType.CONNECT: (
        FLAG_CAP_COALESCE | FLAG_CAP_TRACE | FLAG_CAP_REPLICA
        | FLAG_CAP_QOS | FLAG_QOS_TAIL | FLAG_CAP_FABRIC
        | FLAG_CAP_MUX | FLAG_MUX_TAG | FLAG_CAP_DEADLINE
    ),
    # FLAG_FANOUT: replica-chain role discipline in _check_data_role /
    # _route_put_payload (fan-out legs land, clients need primary role).
    MsgType.DATA_PUT: (
        FLAG_MORE | FLAG_TRACE_CTX | FLAG_FANOUT | FLAG_MUX_TAG
        | FLAG_DEADLINE
    ),
    MsgType.DATA_GET: FLAG_TRACE_CTX | FLAG_MUX_TAG | FLAG_DEADLINE,
    # FLAG_REPLICAS: the data tail's u8 copy count, read in _place_alloc.
    MsgType.REQ_ALLOC: (
        FLAG_TRACE_CTX | FLAG_REPLICAS | FLAG_QOS_TAIL | FLAG_MUX_TAG
        | FLAG_DEADLINE
    ),
    MsgType.DO_ALLOC: FLAG_TRACE_CTX | FLAG_QOS_TAIL | FLAG_DEADLINE,
    MsgType.DO_REPLICA: FLAG_QOS_TAIL | FLAG_DEADLINE,
    # FLAG_QOS_TAIL: the migrated copy inherits the allocation's QoS
    # class — parsed in _on_migrate_begin (elastic/).
    MsgType.MIGRATE_BEGIN: FLAG_QOS_TAIL | FLAG_DEADLINE,
    MsgType.REQ_FREE: FLAG_TRACE_CTX | FLAG_MUX_TAG | FLAG_DEADLINE,
    MsgType.DO_FREE: FLAG_TRACE_CTX | FLAG_DEADLINE,
    MsgType.RECLAIM_APP: FLAG_TRACE_CTX,
    MsgType.NOTE_ALLOC: FLAG_TRACE_CTX,
    MsgType.NOTE_FREE: FLAG_TRACE_CTX,
    # FLAG_HB_FWD: a tombstone-forwarded beat is renewed but never
    # re-relayed (elastic/; the loop-prevention contract).
    MsgType.HEARTBEAT: FLAG_TRACE_CTX | FLAG_HB_FWD | FLAG_MUX_TAG,
    MsgType.STATUS: FLAG_TRACE_CTX | FLAG_MUX_TAG,
    MsgType.STATUS_PROM: FLAG_TRACE_CTX | FLAG_MUX_TAG,
    MsgType.STATUS_EVENTS: FLAG_TRACE_CTX | FLAG_MUX_TAG,
    # Over a mux channel DISCONNECT/REQ_LOCATE are awaited tagged
    # requests (generic tag strip + echo, handlers unchanged).
    MsgType.DISCONNECT: FLAG_MUX_TAG,
    MsgType.REQ_LOCATE: FLAG_MUX_TAG,
    # CANCEL: served inline in _serve_conn's cancel branch (keyed by
    # the victim tag on the SAME connection); _on_cancel covers the
    # lockstep/untagged sender honestly (nothing in flight to revoke).
    MsgType.CANCEL: FLAG_MUX_TAG,
    # shm fabric control legs (fabric/): validated in _shm_entry; the
    # FLAG_CAP_FABRIC offer itself is handled in _on_connect (echo +
    # descriptor tail).
    MsgType.SHM_MAP: FLAG_TRACE_CTX,
    MsgType.SHM_PUT: FLAG_TRACE_CTX,
    MsgType.SHM_GET: FLAG_TRACE_CTX,
}

# Requests a FENCED daemon (one that outlived its own DEAD verdict) must
# refuse with STALE_EPOCH: anything that grants extents or moves data.
# Reads are fenced too — after promotion the replica chain is the truth,
# and a stale primary serving reads would hand back pre-failover bytes.
_FENCED_REJECT = frozenset({
    MsgType.REQ_ALLOC,
    MsgType.DO_ALLOC,
    MsgType.DO_REPLICA,
    MsgType.RE_REPLICATE,
    MsgType.DATA_PUT,
    MsgType.DATA_GET,
    # A fenced daemon must neither drive membership nor move extents:
    # its verdicts were superseded by a newer epoch (elastic/).
    MsgType.REQ_JOIN,
    MsgType.REQ_LEAVE,
    MsgType.MIGRATE,
    MsgType.MIGRATE_BEGIN,
    # A fenced old LEADER must never coordinate (control/): membership
    # announcements, suspicion arbitration, and state replication all
    # bounce STALE_EPOCH so the sender re-aims at the live leader —
    # the split-brain scenario the leader-unique invariant audits.
    MsgType.ADD_NODE,
    MsgType.SUSPECT_NODE,
    MsgType.MASTER_STATE,
    MsgType.LEADER_HANDOFF,
    # The shm fabric's control legs are data ops: a fenced daemon must
    # refuse to bless a segment write OR hand out a mapping — the
    # STALE_EPOCH reply is what sends the client down its failover
    # ladder to the promoted replica (fabric re-resolution).
    MsgType.SHM_MAP,
    MsgType.SHM_PUT,
    MsgType.SHM_GET,
    # The device plane rides the same contract as DATA_*: a fenced owner
    # relaying PLANE_PUT/PLANE_GET would move bytes for extents a newer
    # epoch already re-homed, and a fenced master must not accept plane
    # endpoint registrations (the ADD_NODE rule). Found by the
    # fenced-reject-gap conformance check.
    MsgType.PLANE_SERVE,
    MsgType.PLANE_PUT,
    MsgType.PLANE_GET,
    MsgType.PLANE_SCRUB,
})

_HANDLERS = {
    MsgType.CONNECT: Daemon._on_connect,
    MsgType.DISCONNECT: Daemon._on_disconnect,
    MsgType.ADD_NODE: Daemon._on_add_node,
    MsgType.REQ_ALLOC: Daemon._on_req_alloc,
    MsgType.RECLAIM_APP: Daemon._on_reclaim_app,
    MsgType.DO_ALLOC: Daemon._on_do_alloc,
    MsgType.REQ_FREE: Daemon._on_req_free,
    MsgType.DO_FREE: Daemon._on_do_free,
    MsgType.NOTE_FREE: Daemon._on_note_free,
    MsgType.NOTE_ALLOC: Daemon._on_note_alloc,
    MsgType.DATA_PUT: Daemon._on_data_put,
    MsgType.DATA_GET: Daemon._on_data_get,
    MsgType.SHM_MAP: Daemon._on_shm_map,
    MsgType.SHM_PUT: Daemon._on_shm_put,
    MsgType.SHM_GET: Daemon._on_shm_get,
    MsgType.PLANE_SERVE: Daemon._on_plane_serve,
    MsgType.PLANE_PUT: Daemon._on_plane_relay,
    MsgType.PLANE_GET: Daemon._on_plane_relay,
    MsgType.PLANE_SCRUB: Daemon._on_plane_relay,
    MsgType.HEARTBEAT: Daemon._on_heartbeat,
    MsgType.STATUS: Daemon._on_status,
    MsgType.STATUS_PROM: Daemon._on_status_prom,
    MsgType.STATUS_EVENTS: Daemon._on_status_events,
    MsgType.PING: Daemon._on_ping,
    MsgType.SUSPECT_NODE: Daemon._on_suspect,
    MsgType.EPOCH_UPDATE: Daemon._on_epoch_update,
    MsgType.DO_REPLICA: Daemon._on_do_replica,
    MsgType.PROMOTE: Daemon._on_promote,
    MsgType.RE_REPLICATE: Daemon._on_re_replicate,
    MsgType.REQ_JOIN: Daemon._on_req_join,
    MsgType.REQ_LEAVE: Daemon._on_req_leave,
    MsgType.MEMBER_UPDATE: Daemon._on_member_update,
    MsgType.MIGRATE: Daemon._on_migrate,
    MsgType.MIGRATE_BEGIN: Daemon._on_migrate_begin,
    MsgType.REQ_LOCATE: Daemon._on_req_locate,
    MsgType.REQ_EXTENTS: Daemon._on_req_extents,
    MsgType.CANCEL: Daemon._on_cancel,
    MsgType.MASTER_STATE: Daemon._on_master_state,
    MsgType.LEADER_UPDATE: Daemon._on_leader_update,
    MsgType.LEADER_HANDOFF: Daemon._on_leader_handoff,
}

if __name__ == "__main__":
    raise SystemExit(main())
