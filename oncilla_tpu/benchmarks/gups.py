"""GUPS — giga-updates-per-second random access over the arena fabric.

BASELINE.md config 4 (no reference analogue): measure how fast randomly
addressed words can be updated, (a) within one chip's HBM arena and (b)
across the mesh, where every update targets a random word on a random chip
and rides ICI. TPU-idiomatic formulation: updates are batched scatter-adds
inside one jitted ``fori_loop`` (no per-update dispatch), and the cross-chip
flavor routes each batch with ``lax.all_to_all`` under ``shard_map`` — each
source device draws ``batch // D`` random target words *per destination
device*, so destinations are uniform and shapes stay static.

Updates are ``+1`` on a uint32 table, so correctness is checkable:
``table.sum() == total_updates`` (duplicate indices accumulate).
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from oncilla_tpu.benchmarks._util import fence as _fence
from oncilla_tpu.parallel.mesh import NODE_AXIS, arena_sharding, node_mesh


@partial(jax.jit, donate_argnums=0, static_argnums=(1, 2, 3, 4, 5))
def _gups_single_run(table, steps: int, batch: int, words: int, seed: int,
                     method: str):
    def body(i, t):
        key = jax.random.fold_in(jax.random.key(seed), i)
        idx = jax.random.randint(key, (batch,), 0, words, dtype=jnp.int32)
        if method == "bincount":
            # Histogram formulation: XLA lowers bincount via sort/segment
            # machinery, which can beat the serialized scatter on TPU for
            # dense batches; same semantics (+1 per drawn index).
            return t + jnp.bincount(idx, length=words).astype(jnp.uint32)
        return t.at[idx].add(jnp.uint32(1))

    return jax.lax.fori_loop(0, steps, body, table)


def gups_single(
    words: int = 1 << 20,
    batch: int = 1 << 14,
    steps: int = 64,
    seed: int = 0,
    device=None,
    method: str = "scatter",
) -> dict:
    """Single-chip GUPS on a ``words``-word uint32 HBM table. ``method``
    picks the update lowering ("scatter" or "bincount"); both are exact."""
    def fresh():
        t = jnp.zeros((words,), dtype=jnp.uint32)
        return jax.device_put(t, device) if device is not None else t

    # Warm up with the SAME static args (steps is a static argnum — a
    # different value would recompile inside the timed region).
    _fence(_gups_single_run(fresh(), steps, batch, words, seed, method))
    table = fresh()
    _fence(table)
    t0 = time.perf_counter()
    table = _gups_single_run(table, steps, batch, words, seed, method)
    _fence(table)
    dt = time.perf_counter() - t0
    updates = steps * batch
    total = int(np.asarray(table).astype(np.uint64).sum())
    return {
        "mode": f"single:{method}",
        "gups": updates / dt / 1e9,
        "updates": updates,
        "seconds": dt,
        "table_sum": total,  # == updates (duplicates accumulate)
    }


def gups_single_best(
    words: int = 1 << 20,
    batch: int = 1 << 14,
    steps: int = 64,
    seed: int = 0,
) -> dict:
    """Measure both lowerings, verify conservation on each, keep the best
    (the engine sweet spot differs by backend/generation)."""
    best = None
    for method in ("scatter", "bincount"):
        r = gups_single(words=words, batch=batch, steps=steps, seed=seed,
                        method=method)
        if r["table_sum"] != r["updates"]:
            continue  # wrong results are not publishable
        if best is None or r["gups"] > best["gups"]:
            best = r
    if best is None:
        raise RuntimeError("no GUPS method produced conserved updates")
    return best


# -- handle/arena flavor: the oncilla number ------------------------------
#
# BASELINE config 4 says "random remote-access over ICI via ocm handles";
# the flavors above measure XLA scatter on a standalone table (VERDICT r3
# weak #5). Here the table IS an OcmAlloc extent inside an SpmdIciPlane
# arena row — the same (rank, device, offset) handle-addressed HBM the
# one-sided fabric serves. What the timed program does, precisely: slice
# the extent out of the (donated) arena row, apply ``steps`` batched
# update rounds, write the result back through the extent — the
# slice/bitcast entry+exit is ON the timed path once per run, amortized
# over the rounds rather than paid per round (its per-round form cost
# ~40% of the rate in the r5 first light, and per-round write-back is
# observationally identical inside one jit program anyway). Conservation
# is verified by reading the table back *through the handle*
# (plane.get_as), proving the updates landed in handle-addressable
# memory; what distinguishes this flavor from ``gups_single`` is exactly
# that daemon-issued-extent entry/exit and handle-visible residency, not
# the update kernel.


@partial(jax.jit, donate_argnums=0, static_argnums=(1, 2, 3, 4, 5, 6, 7, 8))
def _gups_handle_run(arena, steps: int, batch: int, words: int, seed: int,
                     off: int, gdev: int, method: str, mesh):
    def shard_fn(shard):  # shard: (1, row_bytes) — this device's arena row
        me = jax.lax.axis_index(NODE_AXIS)
        row = shard[0]

        # Slice + bitcast the extent ONCE around the update loop, not per
        # step (the measurement shape documented in the module comment):
        # the uint8→uint32 bitcast is a cross-lane byte relayout that cost
        # ~40% of the measured rate when paid every iteration (r5 first
        # light: handle 0.051 vs single 0.087 GUPS), and hoisting it is
        # observationally identical — the donated arena row only becomes
        # visible when the jit returns, with or without per-step
        # write-back.
        raw = jax.lax.dynamic_slice(row, (off,), (4 * words,))
        tbl0 = jax.lax.bitcast_convert_type(raw.reshape(words, 4), jnp.uint32)

        def body(i, tbl):
            key = jax.random.fold_in(jax.random.key(seed), i)
            idx = jax.random.randint(key, (batch,), 0, words, dtype=jnp.int32)
            if method == "bincount":
                return tbl + jnp.bincount(idx, length=words).astype(jnp.uint32)
            return tbl.at[idx].add(jnp.uint32(1))

        tbl = jax.lax.fori_loop(0, steps, body, tbl0)
        back = jax.lax.bitcast_convert_type(tbl, jnp.uint8).reshape(-1)
        updated = jax.lax.dynamic_update_slice(row, back, (off,))
        # Only the handle's device mutates its row: on a multi-device plane
        # every other row (and any allocation living there) is untouched,
        # and `updates = steps * batch` counts exactly what landed.
        return jnp.where(me == gdev, updated, row)[None]

    return jax.shard_map(
        shard_fn, mesh=mesh, in_specs=P(NODE_AXIS, None),
        out_specs=P(NODE_AXIS, None),
    )(arena)


def gups_handles(
    words: int = 1 << 20,
    batch: int = 1 << 14,
    steps: int = 32,
    seed: int = 0,
    method: str = "scatter",
    plane=None,
) -> dict:
    """GUPS over an ocm handle allocated END TO END through the control
    plane: an in-process daemon cluster places the table as a device-kind
    allocation (``ctx.alloc``), the plane serves the bytes, and the timed
    program enters the daemon-issued extent once, applies the update
    rounds, and exits back through it (only the handle's device row is
    mutated — see the module comment for the exact measurement shape).
    Reset and conservation read-back go through ``ctx.put``/``ctx.get_as``
    — the full public path. Pass a dedicated bench ``plane`` (or none — a
    fresh loopback plane is made), not one holding live allocations."""
    from oncilla_tpu.core.kinds import OcmKind
    from oncilla_tpu.ops.ici import SpmdIciPlane
    from oncilla_tpu.runtime.cluster import local_cluster
    from oncilla_tpu.utils.config import OcmConfig

    nbytes = 4 * words
    if plane is None:
        from oncilla_tpu.parallel.mesh import node_mesh

        mesh = node_mesh(jax.devices()[:1])
        plane = SpmdIciPlane(
            config=OcmConfig(device_arena_bytes=nbytes + (1 << 20)),
            mesh=mesh, devices_per_rank=1,
        )
    mesh = plane.mesh
    cfg = OcmConfig(
        host_arena_bytes=1 << 20,
        device_arena_bytes=plane.config.device_arena_bytes,
    )
    with local_cluster(1, config=cfg) as cl:
        ctx = cl.context(0, ici_plane=plane)
        # A pad first so the table extent sits at a non-zero offset:
        # proves offset addressing, not row 0. (On a 1-node cluster the
        # REMOTE_DEVICE request demotes to LOCAL_DEVICE, alloc.c:82-83 —
        # still daemon-registered, still plane-resident.)
        pad = ctx.alloc(4096, OcmKind.REMOTE_DEVICE)
        handle = ctx.alloc(nbytes, OcmKind.REMOTE_DEVICE)
        off = handle.extent.offset
        assert off != 0, "pad should push the table off offset 0"
        from oncilla_tpu.ops.ici import resolve_global_device

        gdev = resolve_global_device(
            handle, plane.devices_per_rank, int(mesh.devices.size)
        )

        def run(arena):
            return _gups_handle_run(arena, steps, batch, words, seed, off,
                                    gdev, method, mesh)

        plane.update(run)           # warm-up compiles the timed executable
        ctx.put(handle, np.zeros(nbytes, np.uint8))  # reset via the handle
        _fence(plane.arena[0, :8])
        t0 = time.perf_counter()
        plane.update(run)
        _fence(plane.arena[0, :8])
        dt = time.perf_counter() - t0
        updates = steps * batch
        # Conservation, read back THROUGH the handle via the public API.
        tbl = np.asarray(ctx.get_as(handle, (words,), np.uint32))
        total = int(tbl.astype(np.uint64).sum())
        ctx.free(handle)
        ctx.free(pad)
    return {
        "mode": f"handle:{method}",
        "gups": updates / dt / 1e9,
        "updates": updates,
        "seconds": dt,
        "table_sum": total,  # == updates (duplicates accumulate)
    }


def gups_handle_best(
    words: int = 1 << 20,
    batch: int = 1 << 14,
    steps: int = 32,
    seed: int = 0,
) -> dict:
    """Both lowerings over the same handle-backed table; conservation
    gates publishability, best wins."""
    from oncilla_tpu.ops.ici import SpmdIciPlane
    from oncilla_tpu.parallel.mesh import node_mesh
    from oncilla_tpu.utils.config import OcmConfig

    mesh = node_mesh(jax.devices()[:1])
    plane = SpmdIciPlane(
        config=OcmConfig(device_arena_bytes=4 * words + (1 << 20)),
        mesh=mesh, devices_per_rank=1,
    )
    best = None
    for method in ("scatter", "bincount"):
        r = gups_handles(words=words, batch=batch, steps=steps, seed=seed,
                         method=method, plane=plane)
        if r["table_sum"] != r["updates"]:
            continue  # wrong results are not publishable
        if best is None or r["gups"] > best["gups"]:
            best = r
    if best is None:
        raise RuntimeError("no handle-GUPS method produced conserved updates")
    return best


@partial(jax.jit, donate_argnums=0, static_argnums=(1, 2, 3, 4, 5))
def _gups_mesh_run(table, steps: int, per_dest: int, words: int, seed: int, mesh):
    def shard_fn(shard):  # shard: (1, words) — this device's table row
        me = jax.lax.axis_index(NODE_AXIS)
        d = jax.lax.axis_size(NODE_AXIS)

        def body(i, row):
            key = jax.random.fold_in(jax.random.key(seed), me * 1_000_003 + i)
            # Row j of idx targets device j; all_to_all delivers to it.
            idx = jax.random.randint(
                key, (d, per_dest), 0, words, dtype=jnp.int32
            )
            recv = jax.lax.all_to_all(idx, NODE_AXIS, 0, 0)
            return row.at[recv.reshape(-1)].add(jnp.uint32(1))

        return jax.lax.fori_loop(0, steps, body, shard[0])[None]

    return jax.shard_map(
        shard_fn, mesh=mesh, in_specs=P(NODE_AXIS, None),
        out_specs=P(NODE_AXIS, None),
    )(table)


def gups_mesh(
    mesh=None,
    words_per_dev: int = 1 << 18,
    batch: int = 1 << 12,
    steps: int = 32,
    seed: int = 0,
) -> dict:
    """Cross-chip GUPS: each device issues ``batch`` random updates per step,
    each targeting a uniformly random word on a uniformly random device; the
    index batches ride ICI via all_to_all. The table is laid out exactly like
    the SPMD arena (one row per chip's HBM, ``arena_sharding``)."""
    mesh = mesh if mesh is not None else node_mesh()
    d = mesh.devices.size
    per_dest = max(1, batch // d)
    def fresh():
        return jax.device_put(
            jnp.zeros((d, words_per_dev), dtype=jnp.uint32), arena_sharding(mesh)
        )

    _fence(_gups_mesh_run(fresh(), steps, per_dest, words_per_dev, seed, mesh))
    table = fresh()
    _fence(table)
    t0 = time.perf_counter()
    table = _gups_mesh_run(table, steps, per_dest, words_per_dev, seed, mesh)
    _fence(table)
    dt = time.perf_counter() - t0
    updates = steps * d * d * per_dest  # per step: d sources x d dests x per_dest
    total = int(np.asarray(table).astype(np.uint64).sum())
    return {
        "mode": f"mesh:{d}dev",
        "gups": updates / dt / 1e9,
        "updates": updates,
        "seconds": dt,
        "table_sum": total,  # == updates (duplicates accumulate)
    }


def main() -> None:
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mode", choices=["single", "mesh"], default="single")
    ap.add_argument("--words", type=int, default=1 << 20)
    ap.add_argument("--batch", type=int, default=1 << 14)
    ap.add_argument("--steps", type=int, default=64)
    args = ap.parse_args()

    if args.mode == "mesh":
        out = gups_mesh(
            words_per_dev=args.words, batch=args.batch, steps=args.steps
        )
    else:
        out = gups_single(words=args.words, batch=args.batch, steps=args.steps)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
