"""Seeded NON-violation: self-relay bounded by a terminal flag guard.

Scanned explicitly by tests/test_rpcgraph.py — excluded from default
``python -m oncilla_tpu.analysis`` walks. The GOSSIP handler re-sends
its own type, but every forwarded copy carries FLAG_GOSSIP_FWD and the
handler returns early on flagged input (the FLAG_HB_FWD shape the PR-8
fix introduced) — so a relayed copy can never re-relay. The rpcgraph
scan of this file must be CLEAN; tests/test_rpcgraph.py also deletes
the guard to prove the mutation is caught.
"""


class MsgType:
    GOSSIP = 1
    GOSSIP_OK = 2


FLAG_GOSSIP_FWD = 1 << 0


def Message(msgtype, fields, flags=0):
    return (msgtype, fields, flags)


def _on_gossip(msg, peers, host, port):
    if msg.flags & FLAG_GOSSIP_FWD:
        return Message(MsgType.GOSSIP_OK, {})  # terminal: no re-relay
    peers.request(
        host, port,
        Message(MsgType.GOSSIP, {"seq": 1}, flags=FLAG_GOSSIP_FWD),
    )  # NOT a finding: the relayed copy is flag-terminated above
    return Message(MsgType.GOSSIP_OK, {})


_HANDLERS = {
    MsgType.GOSSIP: _on_gossip,
}
