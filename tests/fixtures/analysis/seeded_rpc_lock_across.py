"""Seeded violation: a ``make_lock`` lock held across a peer dial
(rpcgraph ``lock-across-rpc``).

Scanned explicitly by tests/test_rpcgraph.py — excluded from default
``python -m oncilla_tpu.analysis`` walks. The round-trip happens inside
the ``with _mu:`` scope, so the lock-order edge ``fixture.rpc._mu ->
rpc:daemon`` closes a cross-process cycle with any handler that takes
the same lock. Exactly ONE ``lock-across-rpc`` finding (with
``--families rpcgraph``; the concurrency lint flags the same line
through its own blocking-call rule).
"""

from oncilla_tpu.analysis.lockwatch import make_lock


class MsgType:
    PING = 1


def Message(msgtype, fields, flags=0):
    return (msgtype, fields, flags)


_mu = make_lock("fixture.rpc._mu")


def refresh(peers, host, port):
    with _mu:
        return peers.request(host, port, Message(MsgType.PING, {}))  # FINDING
