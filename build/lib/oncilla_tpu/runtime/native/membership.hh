// Cluster membership shared by the daemon and the C client library:
// NodeEntry + nodefile parsing (struct node_entry / parse_nodefile analogue,
// /root/reference/inc/nodefile.h:19-27, src/nodefile.c:30-37) — mirrors
// oncilla_tpu/runtime/membership.py.

#pragma once

#include <algorithm>
#include <fstream>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace ocm {

struct NodeEntry {
  int64_t rank;
  std::string host;  // DNS name (self-rank detection / logs)
  int port;
  std::string addr;  // connect address column; empty for short-form lines
  // Address peers connect to: the nodefile's addr column when present,
  // else the (possibly ADD_NODE-updated) host. Matches the Python
  // NodeEntry.connect_host contract so mixed Python/C++ clusters route
  // peers identically.
  const std::string& caddr() const { return addr.empty() ? host : addr; }
};

// Accepts "rank host port", "rank host ip port", and the reference's
// "rank host ip ocm_port rdmacm_port" (src/nodefile.c:30-37); the trailing
// per-fabric port is ignored (the TPU data plane is connectionless).
inline std::vector<NodeEntry> parse_nodefile(const std::string& path) {
  std::ifstream f(path);
  if (!f) throw std::runtime_error("cannot open nodefile " + path);
  std::vector<NodeEntry> entries;
  std::string line;
  while (std::getline(f, line)) {
    auto hash = line.find('#');
    if (hash != std::string::npos) line = line.substr(0, hash);
    std::istringstream ss(line);
    std::vector<std::string> tok;
    std::string t;
    while (ss >> t) tok.push_back(t);
    if (tok.empty()) continue;
    NodeEntry e;
    try {
      if (tok.size() == 3) {
        e = {std::stoll(tok[0]), tok[1], std::stoi(tok[2]), ""};
      } else if (tok.size() == 4 || tok.size() == 5) {
        e = {std::stoll(tok[0]), tok[1], std::stoi(tok[3]), tok[2]};
      } else {
        throw std::runtime_error("nodefile line has " +
                                 std::to_string(tok.size()) + " fields");
      }
    } catch (const std::logic_error&) {  // stoi/stoll invalid or overflow
      throw std::runtime_error("bad nodefile line: " + line);
    }
    entries.push_back(e);
  }
  std::sort(entries.begin(), entries.end(),
            [](auto& a, auto& b) { return a.rank < b.rank; });
  for (size_t i = 0; i < entries.size(); ++i)
    if (entries[i].rank != int64_t(i))
      throw std::runtime_error("nodefile ranks must be contiguous from 0");
  return entries;
}

}  // namespace ocm
