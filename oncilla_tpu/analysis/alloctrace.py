"""Runtime allocation ledger (``OCM_ALLOCTRACE=1``).

The static twin (:mod:`~.lifecycle`) sees lexical lifecycles; this ledger
sees the dynamic ones — every allocation that actually happened, who asked
for it, and which ones are still live. Mirrors the :mod:`~.lockwatch`
pattern: disabled (the default) every hook is a cheap early-return; with
``OCM_ALLOCTRACE=1`` each alloc/free records the **call site** (the first
stack frame outside this package — i.e. the app/test line that asked),
the thread name, and a timestamp into the process-global :data:`LEDGER`.

Instrumented layers, each with its own scope prefix so reports separate
cleanly:

- ``ctx:``    :class:`oncilla_tpu.core.context.Ocm` alloc/free (handles)
- ``arena:``  :class:`oncilla_tpu.core.arena.ArenaAllocator` (extents)
- ``daemon:`` :class:`oncilla_tpu.runtime.daemon.Daemon` registry entries

``Ocm.tini()`` asks the ledger for the context's still-live allocations
*before* reclaiming them and emits a structured leak report (also kept as
:func:`last_tini_report` so tests can assert a deliberately-leaked
handle's allocation site shows up). The soak/stress suites run with the
ledger live and assert it drains to empty — the dynamic proof that the
alloc/free books balance under concurrency.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass

__all__ = [
    "enabled", "note_alloc", "note_free", "drop_scope", "live",
    "leak_report", "note_tini", "last_tini_report", "reset",
    "AllocRecord", "AllocLedger", "LEDGER",
]

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def enabled() -> bool:
    return os.environ.get("OCM_ALLOCTRACE", "") not in ("", "0")


def _call_site(skip: int = 2) -> str:
    """``file:line`` of the nearest frame outside oncilla_tpu — the app or
    test line that requested the allocation. Falls back to the outermost
    in-package frame (daemon service threads have all-internal stacks)."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return "<unknown>"
    fallback = "<unknown>"
    depth = 0
    while f is not None and depth < 32:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_DIR):
            return f"{fn}:{f.f_lineno}"
        fallback = f"{fn}:{f.f_lineno}"
        f = f.f_back
        depth += 1
    return fallback


@dataclass(frozen=True)
class AllocRecord:
    scope: str
    alloc_id: int
    nbytes: int
    kind: str
    site: str
    thread: str
    ts: float

    def describe(self) -> dict:
        return {
            "scope": self.scope,
            "alloc_id": self.alloc_id,
            "nbytes": self.nbytes,
            "kind": self.kind,
            "site": self.site,
            "thread": self.thread,
            "age_s": round(time.time() - self.ts, 3),
        }


class AllocLedger:
    """Thread-safe process-global allocation ledger."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._records: dict[tuple[str, int], AllocRecord] = {}
        self.last_tini_report: dict | None = None

    # -- recording ------------------------------------------------------

    def note_alloc(self, scope: str, alloc_id: int, nbytes: int,
                   kind: str = "") -> None:
        if not enabled():
            return
        rec = AllocRecord(
            scope=scope, alloc_id=alloc_id, nbytes=nbytes, kind=kind,
            site=_call_site(2), thread=threading.current_thread().name,
            ts=time.time(),
        )
        with self._mu:
            self._records[(scope, alloc_id)] = rec

    def note_free(self, scope: str, alloc_id: int) -> None:
        if not enabled():
            return
        with self._mu:
            # Unknown keys are silently ignored: frees of allocations made
            # before the ledger was enabled (or restored from a snapshot)
            # must not crash the data path.
            self._records.pop((scope, alloc_id), None)

    def drop_scope(self, scope: str) -> None:
        """Forget a whole scope (arena reset / daemon teardown)."""
        with self._mu:
            for key in [k for k in self._records if k[0] == scope]:
                del self._records[key]

    # -- reporting ------------------------------------------------------

    def live(self, scope_prefix: str | None = None) -> list[AllocRecord]:
        with self._mu:
            recs = list(self._records.values())
        if scope_prefix is not None:
            recs = [r for r in recs if r.scope.startswith(scope_prefix)]
        return sorted(recs, key=lambda r: (r.scope, r.alloc_id))

    def leak_report(self, scope_prefix: str | None = None) -> dict:
        """Structured still-live report: what tini prints and tests assert
        against. ``live`` entries carry the allocation site."""
        recs = self.live(scope_prefix)
        return {
            "scope": scope_prefix or "*",
            "count": len(recs),
            "bytes": sum(r.nbytes for r in recs),
            "live": [r.describe() for r in recs],
        }

    def note_tini(self, scope: str) -> dict:
        """Called by ``Ocm.tini()`` before reclamation; records and
        returns the leak report for that context."""
        report = self.leak_report(scope)
        with self._mu:
            self.last_tini_report = report
        return report

    def reset(self) -> None:
        with self._mu:
            self._records.clear()
            self.last_tini_report = None


LEDGER = AllocLedger()


# Module-level conveniences (the lockwatch idiom).

def note_alloc(scope: str, alloc_id: int, nbytes: int, kind: str = "") -> None:
    LEDGER.note_alloc(scope, alloc_id, nbytes, kind)


def note_free(scope: str, alloc_id: int) -> None:
    LEDGER.note_free(scope, alloc_id)


def drop_scope(scope: str) -> None:
    LEDGER.drop_scope(scope)


def live(scope_prefix: str | None = None) -> list[AllocRecord]:
    return LEDGER.live(scope_prefix)


def leak_report(scope_prefix: str | None = None) -> dict:
    return LEDGER.leak_report(scope_prefix)


def note_tini(scope: str) -> dict:
    return LEDGER.note_tini(scope)


def last_tini_report() -> dict | None:
    return LEDGER.last_tini_report


def reset() -> None:
    LEDGER.reset()
