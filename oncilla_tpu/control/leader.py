"""Master-state replication + the election rule.

The leader's coordination state — what a standby needs to RESUME
coordination (failover, re-replication, rebalance, joins) without the
dead leader — is small: the placement accounting table, the member
view, and the dead set. It replicates as a JSON document with a
trailing CRC32, the exact integrity discipline of snapshot format v2
(:mod:`oncilla_tpu.runtime.snapshot`): a standby that cannot verify the
CRC refuses the copy WHOLE and re-syncs from the survivors rather than
leading from torn state.

The election rule is deliberately trivial and coordination-free: after
a DEAD verdict for the leader, the new leader is the LOWEST-rank live
member. Every rank computes it locally from its own view + detector;
the epoch bump + (rank, incarnation) fence — PR-5's owner-fencing
machinery applied to the master role — is what makes two transient
claimants safe: at most one survives under any epoch, and the
flight-recorder ``leader-unique`` invariant audits exactly that.
"""

from __future__ import annotations

import json
import struct
import zlib

from oncilla_tpu.core.errors import OcmProtocolError

_CRC = struct.Struct("<I")

# Bumped when the document shape changes incompatibly; a standby from a
# newer build refuses an older leader's state (and re-syncs) instead of
# misparsing it.
STATE_VERSION = 1


def pack_state(doc: dict) -> bytes:
    """Encode a master-state document with the CRC32 trailer."""
    doc = dict(doc)
    doc["v"] = STATE_VERSION
    raw = json.dumps(doc, separators=(",", ":")).encode()
    return raw + _CRC.pack(zlib.crc32(raw))


def unpack_state(raw) -> dict:
    """Decode + verify a replicated master-state copy. Raises
    :class:`OcmProtocolError` on ANY integrity failure — truncation, CRC
    mismatch, non-JSON, version skew — so promotion code has exactly one
    refuse-whole path."""
    raw = bytes(raw)
    if len(raw) < _CRC.size + 2:
        raise OcmProtocolError("truncated master state")
    (want,) = _CRC.unpack_from(raw, len(raw) - _CRC.size)
    body = raw[: len(raw) - _CRC.size]
    got = zlib.crc32(body)
    if got != want:
        raise OcmProtocolError(
            f"master-state CRC mismatch (stored {want:#010x}, computed "
            f"{got:#010x}): torn or corrupt — refusing whole"
        )
    try:
        doc = json.loads(body)
    except ValueError as e:
        raise OcmProtocolError(f"malformed master state: {e}") from None
    if not isinstance(doc, dict) or doc.get("v") != STATE_VERSION:
        raise OcmProtocolError(
            f"unsupported master-state version {doc.get('v') if isinstance(doc, dict) else '?'}"
        )
    return doc


def build_state(daemon, seq: int, leader: int | None = None) -> dict:
    """The leader's replicable coordination state, as of now."""
    det = daemon.detector
    return {
        "seq": seq,
        "epoch": daemon.epoch,
        "leader": daemon.rank if leader is None else leader,
        "inc": daemon.incarnation,
        "view": json.loads(daemon.entries.to_wire().decode()),
        "placement": daemon.policy.export_rows(),
        "dead": sorted(det.dead_ranks()) if det is not None else [],
    }


def apply_state(daemon, doc: dict) -> None:
    """Adopt a verified master-state document on a promoting standby:
    member view (epoch-fenced — a stale table is dropped by adopt),
    placement accounting, and the dead set. Idempotent."""
    view = doc.get("view") or {}
    if view:
        daemon.entries.adopt(
            int(view.get("epoch", 0)),
            json.dumps(view, separators=(",", ":")).encode(),
        )
    daemon.policy.restore(doc.get("placement") or [],
                          doc.get("dead") or ())
    daemon._adopt_epoch(int(doc.get("epoch", 0)))
    if daemon.detector is not None:
        for r in doc.get("dead") or ():
            daemon.detector.mark_dead(int(r))


def elect(view, dead, self_rank: int) -> int | None:
    """The election rule: lowest-rank live member (not departed, not in
    the dead set, actually addressable). Every rank runs the same pure
    computation over its own view — returns the winner's rank, or None
    when nobody qualifies."""
    cands = [
        e.rank for e in view
        if e.port
        and e.rank not in dead
        and not view.has_left(e.rank)
    ]
    return min(cands) if cands else None
