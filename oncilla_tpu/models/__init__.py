"""Model-side public surface: the flagship llama family + KV paging.

``serving/`` (and any other runtime consumer) imports the model API
through this package rather than reaching into submodules::

    from oncilla_tpu.models import (
        LlamaConfig, PagedKVCache, BucketedPagedDecoder,
        paged_decode_step_jit,
    )

Attribute access is lazy (PEP 562) so importing a sibling that only
needs one symbol does not eagerly build every model module; submodules
(``models.llama``, ``models.kv_paging``, ...) stay importable directly.
"""

from __future__ import annotations

_EXPORTS = {
    # llama: config + builders + the decode/generate entry points.
    "LlamaConfig": "llama",
    "init_params": "llama",
    "init_params_host": "llama",
    "forward": "llama",
    "loss_fn": "llama",
    "decode_step": "llama",
    "decode_loop": "llama",
    "make_kv_cache": "llama",
    "sample_token": "llama",
    "generate": "llama",
    # kv_paging: the OCM-paged decode family.
    "PagedKVCache": "kv_paging",
    "PagedDecoder": "kv_paging",
    "BucketedPagedDecoder": "kv_paging",
    "paged_decode_step": "kv_paging",
    "paged_decode_step_jit": "kv_paging",
    "paged_decode_batch_step_jit": "kv_paging",
    "paged_decode_page_jit": "kv_paging",
    "paged_generate_page_jit": "kv_paging",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f"{__name__}.{mod}"), name)
