"""``python -m oncilla_tpu.qos`` — the multi-tenant QoS soak harness.

``--soak`` runs dozens of simulated apps (each a real
``ControlPlaneClient`` with its own app id, QoS profile, leases and
heartbeats) with skewed sizes and priorities against an in-process
``local_cluster``, and asserts the QoS contracts end to end:

- **fairness** — every tenant that stays within its quota completes all
  of its alloc/put/get/free rounds; nobody is starved by the hogs.
- **quotas** — an over-quota request gets the typed ``QUOTA_EXCEEDED``
  (and nothing is reserved for it).
- **back-pressure** — low-priority hogs drive every arena past the high
  watermark; REQ_ALLOC answers retryable ``BUSY`` (counted at rank 0)
  and compliant clients absorb it with jittered backoff.
- **priority eviction** — under that pressure the owner reapers evict
  ACTIVE low-priority extents (observed via the eviction counters) and
  never an active normal/high one (the invariant columns stay zero);
  held high-priority data reads back byte-exact afterwards.
- **drained ledger** — after tenants disconnect, every surviving rank's
  registry, arena and OCM_ALLOCTRACE ledger are empty.

With chaos enabled (default; ``--no-chaos`` opts out) the soak also
kills a daemon mid-workload through the PR-5 chaos harness while a
replicated high-priority tenant is writing, and asserts the read after
failover is byte-exact — QoS and failover compose.

``--smoke`` bounds the scenario (fewer tenants/rounds) for CI.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

from oncilla_tpu.qos.policy import PRIO_HIGH, PRIO_LOW, PRIO_NORMAL


def _mk_cfg(base: dict, **over):
    from oncilla_tpu.utils.config import OcmConfig

    kw = dict(base)
    kw.update(over)
    return OcmConfig(**kw)


class _Tenant:
    """One simulated app: its own client (distinct app id ⇒ distinct
    leases/quota), a seeded size distribution, and a success ledger the
    fairness assertion reads."""

    def __init__(self, idx: int, cluster, base_cfg: dict, seed: int,
                 rounds: int):
        import numpy as np

        self.idx = idx
        self.rank = idx % len(cluster.entries)
        self.priority = idx % 3  # low / normal / high, round-robin
        self.quota = 0 if self.priority == PRIO_LOW else (3 << 20)
        self.rounds = rounds
        self.completed = 0
        self.error: BaseException | None = None
        self.rng = np.random.default_rng(seed * 1000 + idx)
        cfg = _mk_cfg(
            base_cfg,
            priority=self.priority,
            quota_bytes=self.quota,
            quota_handles=8 if self.quota else 0,
            busy_retries=6,
            busy_backoff_ms=20,
        )
        from oncilla_tpu.runtime.client import ControlPlaneClient

        self.client = ControlPlaneClient(
            cluster.entries, self.rank, config=cfg,
            app_id=10_000 + idx,
        )
        with cluster._lock:
            cluster.clients.append(self.client)

    def _size(self) -> int:
        # Skewed toward small: most tenants are mice, a few are elephants.
        return int(self.rng.choice(
            [64 << 10, 128 << 10, 256 << 10, 512 << 10],
            p=[0.4, 0.3, 0.2, 0.1],
        ))

    def run_rounds(self) -> None:
        """The fairness workload: alloc, put a seeded pattern, read it
        back byte-exact, free — ``rounds`` times, all within quota."""
        import numpy as np

        from oncilla_tpu.core.kinds import OcmKind

        try:
            for _ in range(self.rounds):
                n = self._size()
                h = self.client.alloc(n, OcmKind.REMOTE_HOST)
                try:
                    data = self.rng.integers(0, 256, n, dtype=np.uint8)
                    self.client.put(h, data)
                    got = self.client.get(h, n)
                    if not np.array_equal(np.asarray(got), data):
                        raise AssertionError(
                            f"tenant {self.idx}: roundtrip mismatch"
                        )
                finally:
                    self.client.free(h)
                self.completed += 1
        except BaseException as e:  # noqa: BLE001 — surfaced by the harness
            self.error = e


def _assert(cond, msg: str) -> None:
    if not cond:
        raise AssertionError(f"qos soak: {msg}")


def run_soak(seed: int, tenants_n: int, rounds: int, chaos: bool,
             verbose: bool = False, mux: bool = False) -> dict:
    import numpy as np

    from oncilla_tpu.analysis import alloctrace
    from oncilla_tpu.core.errors import OcmError, OcmRemoteError
    from oncilla_tpu.core.kinds import OcmKind
    from oncilla_tpu.resilience.chaos import ChaosController, ChaosSchedule
    from oncilla_tpu.runtime.client import ControlPlaneClient
    from oncilla_tpu.runtime.cluster import local_cluster
    from oncilla_tpu.runtime.protocol import ErrCode

    os.environ.setdefault("OCM_ALLOCTRACE", "1")
    alloctrace.reset()
    arena = 24 << 20
    base = dict(
        host_arena_bytes=arena,
        device_arena_bytes=4 << 20,
        lease_s=3.0,
        # Mux mode hosts HUNDREDS of tenants in this one process over
        # one connection per daemon; a 0.2 s beat x 200 tenants would
        # be pure heartbeat load, so the beat relaxes (still ≥4 beats
        # per lease).
        heartbeat_s=0.5 if mux else 0.2,
        arena_high_pct=60,
        arena_low_pct=40,
        chunk_bytes=256 << 10,
        dcn_stripes=2,
        dcn_stripe_min_bytes=1 << 20,
        detect_interval_s=0.05,
        suspect_after=1,
        dead_after=2,
        probe_timeout_s=0.25,
        mux=mux,
    )
    outcome: dict = {"seed": seed, "tenants": tenants_n, "mux": mux}
    with local_cluster(3, config=_mk_cfg(base)) as cl:
        # -- phase A: fairness rounds ---------------------------------
        tenants = [
            _Tenant(i, cl, base, seed, rounds) for i in range(tenants_n)
        ]
        threads = [
            threading.Thread(target=t.run_rounds, name=f"tenant-{t.idx}")
            for t in tenants
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for t in tenants:
            if t.error is not None:
                raise AssertionError(
                    f"qos soak: tenant {t.idx} (prio {t.priority}) failed "
                    f"after {t.completed}/{t.rounds} rounds: "
                    f"{type(t.error).__name__}: {t.error}"
                ) from t.error
        _assert(all(t.completed == t.rounds for t in tenants),
                "a tenant was starved short of its rounds")
        outcome["fair_rounds"] = sum(t.completed for t in tenants)
        if verbose:
            print(f"  fairness: {outcome['fair_rounds']} rounds across "
                  f"{tenants_n} tenants, all complete")

        # -- phase A' (mux only): fd/thread footprint + p99s ----------
        # The ISSUE-13 acceptance pin: the WHOLE tenant fleet shares
        # one connection per live peer (vs O(tenants x stripes) pooled
        # sockets today), and the tail latencies of the storm are in
        # the obs histograms (Tracer bucket counts feed
        # ocm_op_latency_seconds_bucket).
        if mux:
            fp = tenants[0].client.client_footprint()
            peers = len(cl.daemons)
            _assert(
                fp["sockets"] <= peers + 1,
                f"mux fd budget blown: {fp['sockets']} client sockets "
                f"for {peers} peers (want <= peers + 1)",
            )
            snap = tenants[0].client.tracer.snapshot()
            p99s = {
                op: st.get("p99_us")
                for op, st in snap.items() if op.startswith("dcn_")
            }
            _assert(
                any(v for v in p99s.values()),
                "no dcn p99 recorded in the client histograms",
            )
            outcome["footprint"] = {
                "sockets": fp["sockets"],
                "threads": fp["threads"],
                "mux": fp["mux"],
                "p99_us": p99s,
            }
            if verbose:
                print(f"  footprint: {fp['sockets']} sockets / "
                      f"{fp['threads']} threads for {tenants_n} tenants; "
                      f"p99_us={p99s}")

        # -- phase B: quota enforcement -------------------------------
        probe = next(t for t in tenants if t.quota)
        held = probe.client.alloc(2 << 20, OcmKind.REMOTE_HOST)
        try:
            # Must be REJECTED (the assertion below) — nothing to bind.
            probe.client.alloc(2 << 20, OcmKind.REMOTE_HOST)  # ocm-lint: allow[handle-leak-on-path]
            raise AssertionError("qos soak: over-quota alloc was admitted")
        except OcmRemoteError as e:
            _assert(e.code == int(ErrCode.QUOTA_EXCEEDED),
                    f"expected QUOTA_EXCEEDED, got code {e.code}")
        finally:
            probe.client.free(held)
        outcome["quota_rejections"] = 1
        if verbose:
            print("  quota: over-quota alloc rejected QUOTA_EXCEEDED")

        # -- phase C: pressure storm + priority eviction --------------
        # Low-priority hogs allocate-and-hold (no quota, no puts needed:
        # occupancy is reserved bytes) until the cluster crosses the
        # high watermark everywhere and BUSY lands even after their
        # retry budget. Their leases stay ACTIVE (heartbeats running),
        # so the only way the arena recovers is the reaper's
        # priority eviction — which must take hogs, never the active
        # normal/high holders.
        keeper = next(t for t in tenants if t.priority == PRIO_HIGH)
        kn = 1 << 20
        keep_h = keeper.client.alloc(kn, OcmKind.REMOTE_HOST)
        keep_data = keeper.rng.integers(0, 256, kn, dtype=np.uint8)
        keeper.client.put(keep_h, keep_data)

        hogs = [t for t in tenants if t.priority == PRIO_LOW][:3]
        _assert(hogs, "no low-priority tenants to hog with")
        hog_handles: list[tuple[_Tenant, object]] = []
        saw_busy_exhausted = False
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not saw_busy_exhausted:
            for hog in hogs:
                try:
                    hog_handles.append(
                        (hog, hog.client.alloc(1 << 20, OcmKind.REMOTE_HOST))
                    )
                except OcmRemoteError as e:
                    if e.code == int(ErrCode.BUSY):
                        saw_busy_exhausted = True
                        break
                    raise
            if len(hog_handles) > 3 * (arena // (1 << 20)):
                break  # safety: should be unreachable past the watermark
        busy_total = cl.daemons[0].qos.counters["busy"]
        _assert(busy_total > 0,
                f"back-pressure never fired (busy={busy_total})")
        # The reaper must observe pressure and evict ACTIVE low-priority
        # extents; give it a few ticks.
        deadline = time.monotonic() + 15.0
        evicted_low = 0
        while time.monotonic() < deadline:
            evicted_low = sum(
                d.qos.evictions[PRIO_LOW][1] + d.qos.evictions[PRIO_LOW][0]
                for d in cl.daemons
            )
            if evicted_low > 0:
                break
            time.sleep(0.1)
        _assert(evicted_low > 0, "no low-priority eviction under pressure")
        for d in cl.daemons:
            _assert(
                d.qos.evictions[PRIO_NORMAL][1] == 0
                and d.qos.evictions[PRIO_HIGH][1] == 0,
                f"rank {d.rank} evicted an ACTIVE normal/high allocation",
            )
        got = keeper.client.get(keep_h, kn)
        _assert(bytes(got) == keep_data.tobytes(),
                "held high-priority data corrupted by the storm")
        keeper.client.free(keep_h)
        for hog, h in hog_handles:
            try:
                hog.client.free(h)
            except (OcmError, OSError):
                pass  # evicted underneath us: exactly the point
        outcome["busy_total"] = busy_total
        outcome["evicted_low"] = evicted_low
        if verbose:
            print(f"  pressure: busy={busy_total}, low evictions="
                  f"{evicted_low}, high-priority data intact")

        # -- phase D: chaos — daemon kill mid-soak --------------------
        killed_rank = -1
        if chaos:
            ccfg = _mk_cfg(base, replicas=2, priority=PRIO_HIGH)
            cc = ControlPlaneClient(cl.entries, 0, config=ccfg,
                                    app_id=20_000)
            with cl._lock:
                cl.clients.append(cc)
            n = 4 << 20
            h = cc.alloc(n, OcmKind.REMOTE_HOST)
            _assert(h.replica_ranks != (),
                    "replicated placement assigned no replica")
            data = np.random.default_rng(seed).integers(
                0, 256, n, dtype=np.uint8
            )
            cc.put(h, data[: n // 2], 0)
            killed_rank = h.rank if h.rank != 0 else h.replica_ranks[0]
            schedule = ChaosSchedule.kill_at(seed, killed_rank, op=3)
            controller = ChaosController(schedule, cl.entries,
                                         kill_fn=cl.kill)
            with controller.inject():
                step = 512 << 10
                for off in range(n // 2, n, step):
                    cc.put(h, data[off:off + step], off)
                got = cc.get(h, n)
            _assert(bytes(got) == data.tobytes(),
                    "post-kill read is not byte-exact")
            _assert(not controller.pending(),
                    f"chaos schedule unfired: {controller.pending()}")
            _assert(controller.log == [(3, "kill", killed_rank)],
                    f"unexpected chaos log {controller.log}")
            cc.free(h)
            outcome["chaos"] = {
                "killed_rank": killed_rank, "log": list(controller.log),
            }
            if verbose:
                print(f"  chaos: killed rank {killed_rank} mid-put, "
                      f"failover read byte-exact")

        # -- phase E: drain -------------------------------------------
        with cl._lock:
            clients, cl.clients = list(cl.clients), []
        for c in clients:
            c.close()
        survivors = [d for d in cl.daemons if d.rank != killed_rank]
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and any(
            d.registry.live_count() for d in survivors
        ):
            time.sleep(0.1)
        for d in survivors:
            _assert(d.registry.live_count() == 0,
                    f"rank {d.rank} registry not drained "
                    f"({d.registry.live_count()} live)")
            _assert(d.host_arena.allocator.bytes_live == 0,
                    f"rank {d.rank} arena not drained")
        dead_scopes = tuple(
            s for d in cl.daemons if d.rank == killed_rank
            for s in (d._trace_scope,
                      d.host_arena.allocator._trace_scope)
        )
        leaked = [
            r for r in alloctrace.live()
            if not any(r.scope.startswith(s) for s in dead_scopes)
        ]
        _assert(not leaked,
                f"alloctrace ledger leaked: {[r.describe() for r in leaked]}")
        outcome["drained_ranks"] = [d.rank for d in survivors]
    return outcome


def main(argv=None) -> int:
    from oncilla_tpu.utils.platform import honor_cpu_env

    honor_cpu_env()
    ap = argparse.ArgumentParser(
        prog="python -m oncilla_tpu.qos",
        description="multi-tenant QoS soak harness",
    )
    ap.add_argument("--soak", action="store_true",
                    help="run the multi-tenant soak scenario")
    ap.add_argument("--smoke", action="store_true",
                    help="bounded variant for CI (fewer tenants/rounds)")
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--tenants", type=int, default=None)
    ap.add_argument("--rounds", type=int, default=None)
    ap.add_argument("--no-chaos", action="store_true",
                    help="skip the mid-soak daemon kill")
    ap.add_argument("--mux", action="store_true",
                    help="run the tenant fleet over the async mux "
                         "runtime (OCM_MUX): hundreds of tenants in "
                         "this ONE process over one connection per "
                         "daemon, fd budget asserted <= peers + 1")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    if not (args.soak or args.smoke):
        ap.print_help()
        return 2
    mux = args.mux or bool(int(os.environ.get("OCM_MUX", "0") or 0))
    # Mux scale: the serving-scale acceptance runs >= 200 tenants in one
    # process; the smoke keeps CI bounded but still a real multi-tenant
    # fleet over one connection per peer.
    if mux:
        tenants = args.tenants or (24 if args.smoke else 200)
        rounds = args.rounds or (2 if args.smoke else 3)
    else:
        tenants = args.tenants or (6 if args.smoke else 18)
        rounds = args.rounds or (3 if args.smoke else 10)
    label = "smoke" if args.smoke else "soak"
    print(f"qos {label}: seed={args.seed} tenants={tenants} "
          f"rounds={rounds} chaos={not args.no_chaos} mux={mux} ...")
    t0 = time.monotonic()
    try:
        # The soak records under the flight recorder and its timeline
        # must pass the cross-rank invariant audit (obs/audit.py) —
        # eviction priority, fan-out-before-ack, lease termination —
        # on top of the end-state assertions below. Audit findings
        # raise AssertionError with the black-box path.
        from oncilla_tpu.obs import audit as obs_audit

        with obs_audit.recorded(f"qos-{label}") as rec:
            outcome = run_soak(args.seed, tenants, rounds,
                               chaos=not args.no_chaos,
                               verbose=args.verbose, mux=mux)
        print(f"  flight recorder: {rec.summary()}")
    except AssertionError as e:
        print(f"qos {label}: FAIL — {e}", file=sys.stderr)
        return 1
    chaos_note = (
        f", killed rank {outcome['chaos']['killed_rank']} mid-soak"
        if "chaos" in outcome else ""
    )
    mux_note = ""
    if "footprint" in outcome:
        fp = outcome["footprint"]
        mux_note = (
            f", mux fleet: {fp['sockets']} sockets / {fp['threads']} "
            f"threads for {tenants} tenants"
        )
    print(f"qos {label}: OK in {time.monotonic() - t0:.1f}s — "
          f"{outcome['fair_rounds']} fair rounds, "
          f"busy={outcome['busy_total']}, "
          f"low evictions={outcome['evicted_low']}, no active "
          f"normal/high eviction, ledger drained{chaos_note}{mux_note}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
