"""Seeded violation: unbounded network wait on a budgeted path
(rpcgraph ``unbounded-blocking``).

Scanned explicitly by tests/test_rpcgraph.py — excluded from default
``python -m oncilla_tpu.analysis`` walks. The function reads the
ambient timebudget (so it is ON a deadline-carrying path) but then
performs the wire round-trip with no timeout: against a stalled peer
it blocks arbitrarily past its own deadline — the PR-15 class.
Exactly ONE ``unbounded-blocking`` finding.
"""

from oncilla_tpu.resilience import timebudget
from oncilla_tpu.runtime.protocol import request


def fetch(sock, msg):
    bud = timebudget.current()
    if bud is not None and bud.expired:
        raise TimeoutError("budget already spent")
    # Checked the budget, then ignored it for the wait itself.
    return request(sock, msg)  # FINDING: no timeout threaded
