"""Build/launch helpers for the native daemon (oncillamemd).

The Python daemon (runtime/daemon.py) is the executable spec; oncillamemd is
the production twin. Both speak the identical wire protocol, so
ControlPlaneClient works unchanged against either.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
from pathlib import Path

NATIVE_DIR = Path(__file__).resolve().parent
BUILD_DIR = NATIVE_DIR / "build"
BINARY = BUILD_DIR / "oncillamemd"


def _source_fingerprint() -> str:
    """Content hash over the whole native source tree (names + bytes).

    The build cache is keyed on THIS, not on mtimes: mtime comparison
    misses real edits (checkout-normalized or editor-preserved
    timestamps, sub-second truncation on some filesystems, a clock that
    stepped backwards), and a stale cached binary silently runs old
    daemon code under every native test in the session."""
    h = hashlib.sha256()
    srcs = sorted(
        p
        for pat in ("*.cc", "*.c", "*.hh", "*.h", "CMakeLists.txt")
        for p in NATIVE_DIR.glob(pat)
    )
    for p in srcs:
        h.update(p.name.encode())
        h.update(b"\0")
        h.update(p.read_bytes())
        h.update(b"\0")
    return h.hexdigest()


def _stamp_path(target: Path) -> Path:
    return target.with_name(target.name + ".srchash")


def _cached(target: Path, fingerprint: str) -> bool:
    """A target is reusable only when its recorded source fingerprint
    matches the tree exactly; a missing stamp (pre-hash build dirs)
    counts as stale."""
    try:
        return (
            target.exists()
            and _stamp_path(target).read_text().strip() == fingerprint
        )
    except OSError:
        return False


def _write_stamp(target: Path, fingerprint: str) -> None:
    _stamp_path(target).write_text(fingerprint + "\n")


def _run_logged(cmd: list[str], what: str) -> None:
    """Run a build step; on failure raise with the tool's actual output
    (a bare CalledProcessError hides the CMake/compiler error behind
    'returned non-zero exit status', which makes skip messages useless)."""
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except FileNotFoundError as e:
        raise RuntimeError(f"{what} failed: {cmd[0]} not installed") from e
    except subprocess.CalledProcessError as e:
        detail = "\n".join(
            filter(None, [(e.stdout or "")[-2000:], (e.stderr or "")[-2000:]])
        ).strip()
        raise RuntimeError(f"{what} failed (exit {e.returncode}):\n{detail}") from e


def build(force: bool = False, tsan: bool = False) -> Path:
    """Build oncillamemd with CMake (+ Ninja when available); cached,
    keyed on a content hash of the native source tree (mtime staleness
    can miss edits and silently test old daemon code between runs — see
    ``_source_fingerprint``). Containers without cmake fall back to a
    direct compiler invocation of the same two translation units — the
    daemon needs nothing from the build system beyond -pthread, and
    skipping every native test for want of cmake would leave the
    one-protocol property (Python client vs C++ daemon) unverified
    exactly where CI runs."""
    target = BUILD_DIR / ("oncillamemd_tsan" if tsan else "oncillamemd")
    fingerprint = _source_fingerprint()
    if not force and _cached(target, fingerprint):
        return target
    if shutil.which("cmake") is None:
        target = _build_direct(target, tsan)
        _write_stamp(target, fingerprint)
        return target
    gen = ["-G", "Ninja"] if shutil.which("ninja") else []
    cfg = ["cmake", "-S", str(NATIVE_DIR), "-B", str(BUILD_DIR), *gen]
    if tsan:
        cfg.append("-DOCM_TSAN=ON")
    _run_logged(cfg, "cmake configure")
    _run_logged(["cmake", "--build", str(BUILD_DIR)], "cmake build")
    _write_stamp(target, fingerprint)
    return target


def _build_direct(target: Path, tsan: bool) -> Path:
    """cmake-less daemon build: g++/c++ on daemon.cc + protocol.cc with
    the CMakeLists' exact flag set."""
    cxx = shutil.which("g++") or shutil.which("c++") or shutil.which("clang++")
    if cxx is None:
        raise RuntimeError("native build failed: no cmake and no C++ compiler")
    BUILD_DIR.mkdir(parents=True, exist_ok=True)
    cmd = [
        cxx, "-std=c++17", "-Wall", "-Wextra", "-pthread",
        *(["-fsanitize=thread", "-g", "-O1"] if tsan else ["-O2"]),
        str(NATIVE_DIR / "daemon.cc"), str(NATIVE_DIR / "protocol.cc"),
        str(NATIVE_DIR / "obs.cc"),
        "-o", str(target),
    ]
    _run_logged(cmd, "direct compile")
    return target


def spawn(
    nodefile: str,
    rank: int,
    *,
    policy: str = "capacity",
    ndevices: int = 1,
    host_arena_bytes: int | None = None,
    device_arena_bytes: int | None = None,
    lease_s: float | None = None,
    heartbeat_s: float | None = None,
    tsan: bool = False,
    snapshot: str | None = None,
    env: dict | None = None,
    log_path: str | None = None,
    binary: Path | None = None,
) -> subprocess.Popen:
    """Launch one native daemon process (``bin/oncillamem nodefile``
    analogue). Pass ``binary`` (e.g. a fixture's cached build) to skip
    the per-spawn build staleness probe entirely."""
    if binary is None:
        binary = build(tsan=tsan)
    cmd = [
        str(binary),
        "--nodefile", nodefile,
        "--rank", str(rank),
        "--policy", policy,
        "--ndevices", str(ndevices),
    ]
    if host_arena_bytes is not None:
        cmd += ["--host-arena-bytes", str(host_arena_bytes)]
    if device_arena_bytes is not None:
        cmd += ["--device-arena-bytes", str(device_arena_bytes)]
    if lease_s is not None:
        cmd += ["--lease-s", str(lease_s)]
    if heartbeat_s is not None:
        cmd += ["--heartbeat-s", str(heartbeat_s)]
    if snapshot is not None:
        cmd += ["--snapshot", snapshot]
    # Spool output to a file when asked: an undrained PIPE caps at ~64KB and
    # a chatty child (e.g. TSan reports) would block writing to it.
    out = open(log_path, "wb") if log_path is not None else subprocess.PIPE
    try:
        return subprocess.Popen(
            cmd,
            stdout=out,
            stderr=subprocess.STDOUT,
            env={**os.environ, **(env or {})},
        )
    finally:
        if log_path is not None:
            out.close()  # child keeps its own descriptor


def build_lib(force: bool = False) -> Path:
    """Build and return libocm_tpu.so — the C-linkable client library
    (the app-linked libocm.so analogue, /root/reference/SConstruct:176).
    Cached on the same source-tree content hash as :func:`build`."""
    target = BUILD_DIR / "libocm_tpu.so"
    fingerprint = _source_fingerprint()
    if not force and _cached(target, fingerprint):
        return target
    gen = ["-G", "Ninja"] if shutil.which("ninja") else []
    subprocess.run(
        ["cmake", "-S", str(NATIVE_DIR), "-B", str(BUILD_DIR), *gen],
        check=True, capture_output=True,
    )
    subprocess.run(
        ["cmake", "--build", str(BUILD_DIR), "--target", "ocm_tpu", "ocm_c_demo"],
        check=True, capture_output=True,
    )
    _write_stamp(target, fingerprint)
    return target
