"""Decode throughput with OCM-paged KV cache — BASELINE.md config 5.

Measures single-chip tokens/s for a Llama-style decoder in four modes:

- ``fused``: the whole decode as ONE compiled program
  (``llama.decode_loop`` — lax.scan with a donated in-place cache). The
  true ceiling: one host dispatch for the entire sequence.
- ``plain``: per-token ``llama.decode_step`` calls with a donated in-HBM
  cache — the dispatch-per-token reference loop. On a tunneled dev chip
  this is dispatch-latency-bound, so modes with smaller per-step buffers
  (the paged arms) can legitimately exceed it; overhead is therefore
  reported against ``fused``, not ``plain``.
- ``device``: KV history paged through OCM into the chip's HBM *arena*
  (``OcmKind.LOCAL_DEVICE``) via :class:`BucketedPagedDecoder` — on a pod
  the same loop lands pages in a *remote* chip's arena over ICI.
- ``host``: pages ride to host DRAM (``OcmKind.LOCAL_HOST``) — the
  device->host->device round trip is the single-chip analogue of the DCN
  arm.
- ``device_fused``: OCM-paged like ``device`` but ONE dispatch per page
  (``BucketedPagedDecoder.step_page`` — a lax.scan over the page), the
  per-page serving-loop shape that closes most of the dispatch gap to
  ``fused`` while keeping the data plane on the path.

The bucketed decoder keeps shapes static per page (O(tokens/page)
compilations), which is what makes this measurable on real hardware: the
unjitted reference path recompiles every token.

The paged arms run the decoder with ``refetch=True``: every completed page
is shipped out with a one-sided put AND the whole paged context is read
back through one-sided gets at each page boundary, so both directions of
the data plane are on the measured path (the usage pattern of
/root/reference/test/ocm_test.c test 2, with a transformer as the
application; the reference has no ML analogue).
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from oncilla_tpu.benchmarks._util import fence as _sync
from oncilla_tpu.core.kinds import OcmKind
from oncilla_tpu.models import llama
from oncilla_tpu.models.kv_paging import BucketedPagedDecoder


_decode_step = partial(
    jax.jit, static_argnames=("cfg",), donate_argnums=(3,)
)(llama.decode_step)
_decode_loop = partial(
    jax.jit, static_argnames=("cfg",), donate_argnums=(2,)
)(llama.decode_loop)


def _run_cfg(cfg, tokens):
    """Cache sized to the decoded length, not cfg.max_seq, so per-step
    attention work matches the paged arms (a 2048-slot cache for a
    384-token run would understate the reported paging overhead)."""
    import dataclasses

    return dataclasses.replace(cfg, max_seq=tokens.shape[1])


def bench_plain(params, cfg, tokens) -> float:
    """Tokens/s for the dispatch-per-token in-HBM decode loop (donated
    cache, one jit call per token)."""
    cfg = _run_cfg(cfg, tokens)

    def run():
        kv = llama.make_kv_cache(cfg, 1, dtype=cfg.dtype)
        logits = None
        for i in range(tokens.shape[1]):
            logits, kv = _decode_step(
                params, tokens[:, i], jnp.int32(i), kv, cfg
            )
        _sync(logits)

    run()  # compile
    run()  # re-warm: donated outputs settle into steady-state layouts
    t0 = time.perf_counter()
    run()
    return tokens.shape[1] / (time.perf_counter() - t0)


def bench_fused(params, cfg, tokens) -> float:
    """Tokens/s for the whole-sequence scan decode — the single-dispatch
    ceiling every other mode is compared against."""
    cfg = _run_cfg(cfg, tokens)

    def run():
        kv = llama.make_kv_cache(cfg, 1, dtype=cfg.dtype)
        logits, _ = _decode_loop(params, tokens, kv, cfg)
        _sync(logits)

    run()  # compile
    run()  # re-warm (donation layouts)
    t0 = time.perf_counter()
    run()
    return tokens.shape[1] / (time.perf_counter() - t0)


def bench_paged(params, cfg, tokens, ctx, kind, page_tokens) -> float:
    """Tokens/s with KV history paged through OCM handles."""

    def run():
        dec = BucketedPagedDecoder(
            params, cfg, ctx, batch=1, page_tokens=page_tokens, kind=kind,
            dtype=cfg.dtype, refetch=True,
        )
        logits = None
        for i in range(tokens.shape[1]):
            logits = dec.step(tokens[:, i])
        _sync(logits)
        dec.close()

    run()  # compile all page buckets
    t0 = time.perf_counter()
    run()
    return tokens.shape[1] / (time.perf_counter() - t0)


def bench_paged_fused(params, cfg, tokens, ctx, kind, page_tokens) -> float:
    """Tokens/s with OCM-paged KV and ONE dispatch per page
    (BucketedPagedDecoder.step_page): the per-page serving loop — page
    decode scans on-chip, page put/get through the data plane between
    dispatches (still refetch=True, so both directions are measured)."""
    n_pages = tokens.shape[1] // page_tokens

    def run():
        dec = BucketedPagedDecoder(
            params, cfg, ctx, batch=1, page_tokens=page_tokens, kind=kind,
            dtype=cfg.dtype, refetch=True,
        )
        logits = None
        for p in range(n_pages):
            logits = dec.step_page(
                tokens[:, p * page_tokens:(p + 1) * page_tokens]
            )
        _sync(logits)
        dec.close()

    run()  # compile all page buckets
    t0 = time.perf_counter()
    run()
    return n_pages * page_tokens / (time.perf_counter() - t0)


def run_bench(
    tokens_n: int = 384,
    page_tokens: int = 128,
    # Scan-heavy modes run LAST: donating buffers through a big scan
    # executable leaves the chip in a state where subsequent per-step
    # dispatch loses 2-3x throughput (same stickiness bench.py documents
    # for the DMA loops) — measured: plain reads 196 tok/s before fused,
    # 73 after. device_fused (one scan per page) sits just before fused.
    modes: tuple = ("plain", "device", "host", "device_fused", "fused"),
    config: str = "small",
) -> dict:
    """Programmatic entry (bench.py and the CLI share it): tokens/s per
    mode plus the paging overhead vs the in-HBM ceiling."""
    import oncilla_tpu as ocm

    cfg = llama.LlamaConfig() if config == "small" else llama.LlamaConfig.tiny()
    params = llama.init_params_host(0, cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab, size=(1, tokens_n), dtype=np.int32)
    )

    # Arena sized for all pages of the run (both timed + warmup sessions
    # free their pages on close).
    page_bytes = (
        2 * cfg.n_layers * cfg.n_kv_heads * page_tokens * cfg.head_dim
        * jnp.dtype(cfg.dtype).itemsize
    )
    npages = tokens_n // page_tokens
    arena = max(64 << 20, 2 * npages * page_bytes)
    ctx = ocm.ocm_init(
        ocm.OcmConfig(host_arena_bytes=arena, device_arena_bytes=arena)
    )

    out = {"config": config, "tokens": tokens_n,
           "page_tokens": page_tokens, "tok_s": {}}
    try:
        _run_modes(out, modes, params, cfg, tokens, ctx, page_tokens)
    finally:
        ocm.ocm_tini(ctx)  # never leak the arenas into the caller's process
    return out


def _run_modes(out, modes, params, cfg, tokens, ctx, page_tokens):
    for mode in modes:
        if mode == "fused":
            tps = bench_fused(params, cfg, tokens)
        elif mode == "plain":
            tps = bench_plain(params, cfg, tokens)
        elif mode == "device":
            tps = bench_paged(
                params, cfg, tokens, ctx, OcmKind.LOCAL_DEVICE, page_tokens
            )
        elif mode == "host":
            tps = bench_paged(
                params, cfg, tokens, ctx, OcmKind.LOCAL_HOST, page_tokens
            )
        elif mode == "device_fused":
            tps = bench_paged_fused(
                params, cfg, tokens, ctx, OcmKind.LOCAL_DEVICE, page_tokens
            )
        else:
            raise ValueError(f"unknown mode {mode!r}")
        out["tok_s"][mode] = round(tps, 2)

    # Paging overhead of the PAGED arms only, vs the single-dispatch
    # ceiling (falling back to the per-step loop when fused wasn't
    # requested). plain's gap vs fused is dispatch latency, not paging —
    # it stays out of this dict.
    base_mode = "fused" if "fused" in out["tok_s"] else "plain"
    if base_mode in out["tok_s"]:
        base = out["tok_s"][base_mode]
        out["overhead_vs"] = base_mode
        out["paging_overhead"] = {
            m: round(base / v - 1.0, 4)
            for m, v in out["tok_s"].items()
            if m in ("device", "host", "device_fused") and v
        }


def main() -> None:
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--tokens", type=int, default=384)
    ap.add_argument("--page-tokens", type=int, default=128)
    ap.add_argument(
        "--modes", default="plain,device,host,device_fused,fused",
        help="comma list of plain|device|host|device_fused|fused (scan "
             "modes last: see run_bench on measurement-order sensitivity)",
    )
    ap.add_argument("--config", choices=["small", "tiny"], default="small")
    args = ap.parse_args()
    try:
        out = run_bench(
            tokens_n=args.tokens,
            page_tokens=args.page_tokens,
            modes=tuple(m.strip() for m in args.modes.split(",") if m.strip()),
            config=args.config,
        )
    except ValueError as e:
        raise SystemExit(str(e)) from e
    print(json.dumps(out))


if __name__ == "__main__":
    main()
