"""DCN data-plane bandwidth: the daemon-served one-sided put/get path.

BASELINE config 2 — "2-host remote alloc + one-sided put/get (daemon
path)" (≙ the reference's ocm_test test 2 / extoll_rma2_transfer timing,
/root/reference/test/ocm_test.c:132-206, src/extoll.c:47-173). Two
daemons on this host, a client attached to rank 0, a REMOTE_HOST
allocation placed on rank 1, and timed whole-region put/get through the
chunked pipelined engine (16 MiB x 2 in flight; see OcmConfig's
chunk_bytes rationale). On one host this rides
loopback TCP, so the number is an upper bound on protocol+engine
overhead rather than a fabric measurement — but unlike every chip
metric it needs no TPU, so a wedged-tunnel bench still banks it.
"""

from __future__ import annotations

import contextlib
import tempfile
import time

import numpy as np

from oncilla_tpu.core.context import Ocm
from oncilla_tpu.core.kinds import OcmKind
from oncilla_tpu.runtime.client import ControlPlaneClient
from oncilla_tpu.runtime.membership import NodeEntry
from oncilla_tpu.utils.config import OcmConfig


@contextlib.contextmanager
def _daemon_pair(cfg: OcmConfig, native: bool):
    """Two REAL daemon processes on loopback (the C++ twin when built,
    else python subprocesses) — in-process daemon threads would share the
    client's GIL and understate the data plane by ~2x."""
    import os
    import socket
    import subprocess
    import sys

    ports = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    nf = tempfile.NamedTemporaryFile("w", suffix=".nodes", delete=False)
    nf.write("".join(
        f"{r} localhost 127.0.0.1 {p}\n" for r, p in enumerate(ports)
    ))
    nf.close()
    entries = [NodeEntry(r, "127.0.0.1", p) for r, p in enumerate(ports)]
    procs = []
    try:
        if native:
            from oncilla_tpu.runtime.native import native as nat

            nat.build()
            for r in range(2):
                procs.append(nat.spawn(
                    nf.name, r, ndevices=1,
                    host_arena_bytes=cfg.host_arena_bytes,
                    device_arena_bytes=cfg.device_arena_bytes,
                    heartbeat_s=5.0, lease_s=120.0,
                ))
        else:
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            for r in range(2):
                procs.append(subprocess.Popen(
                    [sys.executable, "-m", "oncilla_tpu.runtime.daemon",
                     nf.name, "--rank", str(r),
                     "--host-arena-bytes", str(cfg.host_arena_bytes),
                     "--device-arena-bytes", str(cfg.device_arena_bytes)],
                    env=env,
                ))
        deadline = time.time() + 60
        for e in entries:
            while time.time() < deadline:
                try:
                    socket.create_connection((e.host, e.port), 0.5).close()
                    break
                except OSError:
                    time.sleep(0.1)
            else:
                raise RuntimeError("bench daemon did not come up")
        yield entries
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                p.kill()
        os.unlink(nf.name)


def dcn_loopback_bench(
    nbytes: int = 256 << 20,
    iters: int = 3,
    chunk_bytes: int = 16 << 20,
    inflight: int = 2,
    native: bool = True,
) -> dict:
    """Timed put/get of a ``nbytes`` REMOTE_HOST region through two live
    daemon PROCESSES (loopback). Returns GB/s per direction (best of
    ``iters``) plus the verified-roundtrip flag."""
    cfg = OcmConfig(
        host_arena_bytes=nbytes + chunk_bytes,
        device_arena_bytes=1 << 20,
        chunk_bytes=chunk_bytes,
        inflight_ops=inflight,
        heartbeat_s=5.0,
    )
    with _daemon_pair(cfg, native=native) as entries:
        client = ControlPlaneClient(entries, 0, config=cfg, heartbeat=False)
        # Full membership before placement (a 1-node cluster demotes).
        deadline = time.time() + 30
        while time.time() < deadline and client.status()["nnodes"] < 2:
            time.sleep(0.1)
        # devices=[] — this bench is host-kind only, and the default
        # jax.local_devices() probe would HANG on a wedged TPU tunnel
        # (this stage runs on the bench's wedge path precisely because it
        # needs no chip).
        ctx = Ocm(config=cfg, remote=client, devices=[])
        h = ctx.alloc(nbytes, OcmKind.REMOTE_HOST)
        assert h.is_remote, "placement demoted; membership race?"
        data = np.random.default_rng(0).integers(
            0, 256, nbytes, dtype=np.uint8
        )
        put_s, get_s = [], []
        got = None
        for _ in range(iters):
            t0 = time.perf_counter()
            ctx.put(h, data)
            put_s.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            got = np.asarray(ctx.get(h))
            get_s.append(time.perf_counter() - t0)
        ok = bool(np.array_equal(got, data))
        ctx.free(h)
        client.close()
    return {
        "put_gbps": nbytes / min(put_s) / 1e9,
        "get_gbps": nbytes / min(get_s) / 1e9,
        "nbytes": nbytes,
        "iters": iters,
        "native_daemons": native,
        "verified": ok,
    }
