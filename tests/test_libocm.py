"""libocm_tpu.so — the C-linkable client library — driven via ctypes against
both the C++ and the Python daemons (the app-linked libocm.so capability of
the reference, /root/reference/SConstruct:176 + inc/oncillamem.h)."""

import ctypes
import socket
import time

import numpy as np
import pytest

from _helpers import free_ports, wait_nnodes
from oncilla_tpu.runtime.membership import NodeEntry


class OcmcHandle(ctypes.Structure):
    _fields_ = [
        ("alloc_id", ctypes.c_uint64),
        ("rank", ctypes.c_int64),
        ("device_index", ctypes.c_uint32),
        ("kind", ctypes.c_uint8),
        ("nbytes", ctypes.c_uint64),
        ("offset", ctypes.c_uint64),
        ("owner_host", ctypes.c_char * 256),
        ("owner_port", ctypes.c_uint32),
    ]


@pytest.fixture(scope="module")
def lib():
    from oncilla_tpu.runtime.native import native

    try:
        path = native.build_lib()
    except Exception as e:  # noqa: BLE001
        pytest.skip(f"native build unavailable: {e}")
    L = ctypes.CDLL(str(path))
    L.ocmc_init.restype = ctypes.c_void_p
    L.ocmc_init.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_double]
    L.ocmc_tini.argtypes = [ctypes.c_void_p]
    L.ocmc_alloc.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint8,
        ctypes.POINTER(OcmcHandle),
    ]
    L.ocmc_free.argtypes = [ctypes.c_void_p, ctypes.POINTER(OcmcHandle)]
    L.ocmc_put.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(OcmcHandle), ctypes.c_void_p,
        ctypes.c_uint64, ctypes.c_uint64,
    ]
    L.ocmc_get.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(OcmcHandle), ctypes.c_void_p,
        ctypes.c_uint64, ctypes.c_uint64,
    ]
    L.ocmc_is_remote.argtypes = [ctypes.POINTER(OcmcHandle)]
    L.ocmc_remote_sz.restype = ctypes.c_uint64
    L.ocmc_remote_sz.argtypes = [ctypes.POINTER(OcmcHandle)]
    L.ocmc_nnodes.restype = ctypes.c_int64
    L.ocmc_nnodes.argtypes = [ctypes.c_void_p]
    L.ocmc_last_error.restype = ctypes.c_char_p
    L.ocmc_last_error.argtypes = [ctypes.c_void_p]
    L.ocmc_localbuf.restype = ctypes.c_void_p
    L.ocmc_localbuf.argtypes = [ctypes.c_void_p, ctypes.POINTER(OcmcHandle)]
    L.ocmc_localbuf_sized.restype = ctypes.c_void_p
    L.ocmc_localbuf_sized.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(OcmcHandle), ctypes.c_uint64,
    ]
    L.ocmc_copy_onesided.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(OcmcHandle), ctypes.c_int,
    ]
    L.ocmc_copy.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(OcmcHandle),
        ctypes.POINTER(OcmcHandle), ctypes.c_uint64,
    ]
    L.ocmc_copy_out.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.POINTER(OcmcHandle),
        ctypes.c_uint64, ctypes.c_uint64,
    ]
    L.ocmc_copy_in.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(OcmcHandle), ctypes.c_void_p,
        ctypes.c_uint64, ctypes.c_uint64,
    ]
    return L


def _wait_cluster(ports, n=2, deadline_s=15.0):
    if not wait_nnodes(ports[0], n, deadline_s):
        pytest.fail("daemons did not form a cluster")


@pytest.fixture(params=["native", "python"])
def cluster(request, tmp_path):
    """Two daemons (C++ or Python) + the nodefile path."""
    ports = free_ports(2)
    nodefile = tmp_path / "nodefile"
    nodefile.write_text(
        "".join(f"{r} 127.0.0.1 {p}\n" for r, p in enumerate(ports))
    )
    if request.param == "native":
        from oncilla_tpu.runtime.native import native

        try:
            native.build()
        except Exception as e:  # noqa: BLE001
            pytest.skip(f"native build unavailable: {e}")
        procs = [
            native.spawn(str(nodefile), r, host_arena_bytes=8 << 20)
            for r in range(2)
        ]
        try:
            _wait_cluster(ports)
            yield str(nodefile)
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                p.wait(timeout=5)
    else:
        from oncilla_tpu.runtime.daemon import Daemon
        from oncilla_tpu.utils.config import OcmConfig

        entries = [NodeEntry(r, "127.0.0.1", p) for r, p in enumerate(ports)]
        cfg = OcmConfig(host_arena_bytes=8 << 20)
        daemons = [Daemon(r, entries, config=cfg) for r in range(2)]
        for d in daemons:
            d.start()
        try:
            _wait_cluster(ports)
            yield str(nodefile)
        finally:
            for d in daemons:
                d.stop()


def test_c_client_roundtrip(lib, cluster):
    ctx = lib.ocmc_init(cluster.encode(), 0, 0.0)
    assert ctx, lib.ocmc_last_error(None)
    try:
        assert lib.ocmc_nnodes(ctx) == 2
        h = OcmcHandle()
        assert lib.ocmc_alloc(ctx, 1 << 20, 3, ctypes.byref(h)) == 0  # REMOTE_HOST
        assert h.rank == 1 and lib.ocmc_is_remote(ctypes.byref(h)) == 1
        assert lib.ocmc_remote_sz(ctypes.byref(h)) == 1 << 20

        data = np.random.default_rng(0).integers(
            0, 256, 1 << 20, dtype=np.uint8
        )
        assert lib.ocmc_put(
            ctx, ctypes.byref(h),
            data.ctypes.data_as(ctypes.c_void_p), data.nbytes, 0,
        ) == 0
        out = np.zeros_like(data)
        assert lib.ocmc_get(
            ctx, ctypes.byref(h),
            out.ctypes.data_as(ctypes.c_void_p), out.nbytes, 0,
        ) == 0
        np.testing.assert_array_equal(out, data)

        # offset round trip
        assert lib.ocmc_put(
            ctx, ctypes.byref(h),
            data.ctypes.data_as(ctypes.c_void_p), 1024, 4096,
        ) == 0
        out2 = np.zeros(1024, dtype=np.uint8)
        assert lib.ocmc_get(
            ctx, ctypes.byref(h),
            out2.ctypes.data_as(ctypes.c_void_p), 1024, 4096,
        ) == 0
        np.testing.assert_array_equal(out2, data[:1024])

        assert lib.ocmc_free(ctx, ctypes.byref(h)) == 0
    finally:
        lib.ocmc_tini(ctx)


def test_c_client_errors(lib, cluster):
    ctx = lib.ocmc_init(cluster.encode(), 0, 0.0)
    assert ctx, lib.ocmc_last_error(None)
    try:
        h = OcmcHandle()
        assert lib.ocmc_alloc(ctx, 4096, 3, ctypes.byref(h)) == 0

        # out-of-bounds put -> daemon ERR -> -1 with a message
        buf = np.zeros(8192, dtype=np.uint8)
        rc = lib.ocmc_put(
            ctx, ctypes.byref(h),
            buf.ctypes.data_as(ctypes.c_void_p), 8192, 0,
        )
        assert rc == -1
        assert b"daemon error" in lib.ocmc_last_error(ctx)

        # the connection survives the error: a valid op still works
        assert lib.ocmc_put(
            ctx, ctypes.byref(h),
            buf.ctypes.data_as(ctypes.c_void_p), 4096, 0,
        ) == 0
        assert lib.ocmc_free(ctx, ctypes.byref(h)) == 0
        # double free fails cleanly
        assert lib.ocmc_free(ctx, ctypes.byref(h)) == -1

        # Device-kind data with NO plane registered anywhere: the owner
        # daemon refuses the relayed op with a typed error naming the fix
        # (when a controller serves a plane this same call succeeds —
        # tests/test_plane_relay.py::test_libocm_c_abi_device_roundtrip).
        hd = OcmcHandle()
        assert lib.ocmc_alloc(ctx, 4096, 2, ctypes.byref(hd)) == 0  # REMOTE_DEVICE
        rc = lib.ocmc_put(
            ctx, ctypes.byref(hd),
            buf.ctypes.data_as(ctypes.c_void_p), 4096, 0,
        )
        assert rc == -1 and b"registered plane" in lib.ocmc_last_error(ctx)
        assert lib.ocmc_free(ctx, ctypes.byref(hd)) == 0
    finally:
        lib.ocmc_tini(ctx)


def test_c_client_init_failure(lib, tmp_path):
    bad = tmp_path / "nf"
    bad.write_text("0 127.0.0.1 1\n")  # port 1: nothing listening
    ctx = lib.ocmc_init(str(bad).encode(), 0, 0.0)
    assert not ctx
    assert b"connect failed" in lib.ocmc_last_error(None)


def test_c_demo_program(cluster):
    # The pure-C demo app (ocm_test.c test-2 shape) against live daemons.
    import subprocess

    from oncilla_tpu.runtime.native import native

    try:
        native.build_lib()
    except Exception as e:  # noqa: BLE001
        pytest.skip(f"native build unavailable: {e}")
    demo = native.BUILD_DIR / "ocm_c_demo"
    if not demo.exists():
        pytest.skip("ocm_c_demo not built")
    r = subprocess.run(
        [str(demo), cluster, "0", str(1 << 20)],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert r.stdout.count("pass:") == 3, r.stdout  # put/get, localbuf, copy


def test_c_client_multithreaded(lib, cluster):
    """libocm_tpu.so under real thread concurrency: ctypes releases the GIL
    for the duration of each C call, so 8 Python threads drive the library's
    ctrl/data paths (ctrl_mu, per-connection mu, owners map, last_error TLS)
    concurrently. Each thread does its own alloc -> pattern put/get -> free
    loop; any lost update, cross-talk, or error-state bleed fails the
    assertions."""
    import threading

    ctx = lib.ocmc_init(cluster.encode(), 0, 0.05)  # heartbeats on too
    assert ctx, lib.ocmc_last_error(None)
    errs = []

    def worker(tid):
        try:
            rng = np.random.default_rng(tid)
            for it in range(6):
                h = OcmcHandle()
                nbytes = int(rng.integers(1, 64)) << 10
                assert lib.ocmc_alloc(ctx, nbytes, 3, ctypes.byref(h)) == 0, \
                    lib.ocmc_last_error(ctx)
                data = rng.integers(0, 256, nbytes, dtype=np.uint8)
                assert lib.ocmc_put(
                    ctx, ctypes.byref(h),
                    data.ctypes.data_as(ctypes.c_void_p), nbytes, 0,
                ) == 0, lib.ocmc_last_error(ctx)
                out = np.zeros_like(data)
                assert lib.ocmc_get(
                    ctx, ctypes.byref(h),
                    out.ctypes.data_as(ctypes.c_void_p), nbytes, 0,
                ) == 0, lib.ocmc_last_error(ctx)
                np.testing.assert_array_equal(out, data)
                # Every other iteration, provoke an error to stress the
                # thread-local last_error snapshotting under concurrency.
                if it % 2 == 0:
                    bad = np.zeros(nbytes + 4096, dtype=np.uint8)
                    rc = lib.ocmc_put(
                        ctx, ctypes.byref(h),
                        bad.ctypes.data_as(ctypes.c_void_p), nbytes + 4096, 0,
                    )
                    assert rc == -1
                    assert b"daemon error" in lib.ocmc_last_error(ctx)
                assert lib.ocmc_free(ctx, ctypes.byref(h)) == 0, \
                    lib.ocmc_last_error(ctx)
        except Exception as e:  # noqa: BLE001
            errs.append(f"thread {tid}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "worker wedged"
    lib.ocmc_tini(ctx)
    assert not errs, errs


def test_daemon_survives_garbage_bytes(cluster):
    """Random bytes on the control port must not take the daemon down
    (untrusted wire input): the connection may drop, but a well-formed
    request on a fresh connection still works."""
    import numpy as np

    from oncilla_tpu.runtime.membership import parse_nodefile
    from oncilla_tpu.runtime.protocol import Message, MsgType, request

    e = parse_nodefile(cluster)[0]
    rng = np.random.default_rng(99)
    for _ in range(20):
        s = socket.create_connection((e.connect_host, e.port), timeout=2.0)
        try:
            s.sendall(bytes(rng.integers(0, 256, int(rng.integers(1, 200)),
                                         dtype=np.uint8)))
        finally:
            s.close()
    # A complete frame whose payload is truncated for its schema (CONNECT
    # needs 16 bytes of fields): this drives the decoder's
    # malformed-payload path, not the short-read path.
    s = socket.create_connection((e.connect_host, e.port), timeout=2.0)
    try:
        s.sendall(
            b"OCM1" + bytes([2, 1, 0, 0]) + (3).to_bytes(4, "little") + b"abc"
        )
    finally:
        s.close()

    s = socket.create_connection((e.connect_host, e.port), timeout=5.0)
    try:
        st = request(s, Message(MsgType.STATUS, {}))
        assert st.type == MsgType.STATUS_OK
    finally:
        s.close()


def test_c_client_localbuf_copy_surface(lib, cluster, rng):
    """The rest of the oncillamem.h surface from C: localbuf staging +
    copy_onesided (op_flag convention), handle-to-handle ocmc_copy, and the
    copy_out/copy_in pair the reference left as -1 stubs."""
    ctx = lib.ocmc_init(cluster.encode(), 0, 0.0)
    assert ctx, lib.ocmc_last_error(None)
    try:
        n = 256 << 10
        h1, h2 = OcmcHandle(), OcmcHandle()
        assert lib.ocmc_alloc(ctx, n, 3, ctypes.byref(h1)) == 0
        assert lib.ocmc_alloc(ctx, n, 3, ctypes.byref(h2)) == 0

        # localbuf: stable staging window; write through it with
        # copy_onesided(op_flag=1), read back with op_flag=0.
        p = lib.ocmc_localbuf(ctx, ctypes.byref(h1))
        assert p and p == lib.ocmc_localbuf(ctx, ctypes.byref(h1))
        stage = (ctypes.c_uint8 * n).from_address(p)
        data = rng.integers(0, 256, n, dtype=np.uint8)
        stage[:] = data.tolist()
        assert lib.ocmc_copy_onesided(ctx, ctypes.byref(h1), 1) == 0
        ctypes.memset(p, 0, n)
        assert lib.ocmc_copy_onesided(ctx, ctypes.byref(h1), 0) == 0
        np.testing.assert_array_equal(np.ctypeslib.as_array(stage), data)

        # Handle-to-handle copy, then read the destination out.
        assert lib.ocmc_copy(ctx, ctypes.byref(h2), ctypes.byref(h1), 0) == 0
        out = np.zeros(n, dtype=np.uint8)
        assert lib.ocmc_copy_out(
            ctx, out.ctypes.data_as(ctypes.c_void_p), ctypes.byref(h2), n, 0,
        ) == 0
        np.testing.assert_array_equal(out, data)

        # copy_in at an offset.
        patch = rng.integers(0, 256, 1024, dtype=np.uint8)
        assert lib.ocmc_copy_in(
            ctx, ctypes.byref(h2),
            patch.ctypes.data_as(ctypes.c_void_p), 1024, 4096,
        ) == 0
        out2 = np.zeros(1024, dtype=np.uint8)
        assert lib.ocmc_copy_out(
            ctx, out2.ctypes.data_as(ctypes.c_void_p), ctypes.byref(h2),
            1024, 4096,
        ) == 0
        np.testing.assert_array_equal(out2, patch)

        # Oversized copy is rejected with a message, not clamped.
        small = OcmcHandle()
        assert lib.ocmc_alloc(ctx, 4096, 3, ctypes.byref(small)) == 0
        assert lib.ocmc_copy(ctx, ctypes.byref(small), ctypes.byref(h1), n) == -1
        assert b"exceeds" in lib.ocmc_last_error(ctx)

        for h in (h1, h2, small):
            assert lib.ocmc_free(ctx, ctypes.byref(h)) == 0
    finally:
        lib.ocmc_tini(ctx)


def test_c_client_sized_window(lib, cluster, rng):
    """Asymmetric staging window from C (ocmc_localbuf_sized): a 4 KiB
    window slides over a 64 KiB remote region via put/get offsets; the
    reference's local_alloc_bytes idiom (ocm_test.c:35-47)."""
    ctx = lib.ocmc_init(cluster.encode(), 0, 0.0)
    assert ctx, lib.ocmc_last_error(None)
    try:
        h = OcmcHandle()
        assert lib.ocmc_alloc(ctx, 64 << 10, 3, ctypes.byref(h)) == 0
        p = lib.ocmc_localbuf_sized(ctx, ctypes.byref(h), 4 << 10)
        assert p
        # Same pointer on repeat; resize rejected.
        assert lib.ocmc_localbuf(ctx, ctypes.byref(h)) == p
        assert not lib.ocmc_localbuf_sized(ctx, ctypes.byref(h), 8 << 10)
        assert b"different size" in lib.ocmc_last_error(ctx)

        stage = (ctypes.c_uint8 * (4 << 10)).from_address(p)
        data = rng.integers(0, 256, 4 << 10, dtype=np.uint8)
        stage[:] = data.tolist()
        assert lib.ocmc_put(ctx, ctypes.byref(h), p, 4 << 10, 32 << 10) == 0
        out = np.zeros(4 << 10, dtype=np.uint8)
        assert lib.ocmc_get(
            ctx, ctypes.byref(h), out.ctypes.data_as(ctypes.c_void_p),
            4 << 10, 32 << 10,
        ) == 0
        np.testing.assert_array_equal(out, data)

        # copy_onesided moves only the window (from remote offset 0).
        assert lib.ocmc_copy_onesided(ctx, ctypes.byref(h), 1) == 0
        assert lib.ocmc_get(
            ctx, ctypes.byref(h), out.ctypes.data_as(ctypes.c_void_p),
            4 << 10, 0,
        ) == 0
        np.testing.assert_array_equal(out, data)
        assert lib.ocmc_free(ctx, ctypes.byref(h)) == 0
    finally:
        lib.ocmc_tini(ctx)
