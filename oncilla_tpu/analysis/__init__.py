"""Project-native static analysis for the Python control plane.

The reference OncillaMem shipped known data races (reply-before-listen
mem.c:350-354, unlocked shared lists rdma.c:147-149 — SURVEY.md §5.2) with
zero tooling to catch them. The native daemon gets ThreadSanitizer coverage
(tests/test_native_tsan.py); this package is the Python-side twin:

- :mod:`~oncilla_tpu.analysis.lint` — AST checks tuned to THIS codebase:
  blocking calls inside ``with <lock>:`` scopes, silently swallowed broad
  exceptions in runtime paths, host-side numpy calls inside ``jax.jit``-
  traced functions.
- :mod:`~oncilla_tpu.analysis.lifecycle` — CFG-based intraprocedural
  dataflow over alloc handles: ``handle-leak-on-path``,
  ``use-after-free``, ``double-free``.
- :mod:`~oncilla_tpu.analysis.alloctrace` — the lifecycle pass's runtime
  twin (``OCM_ALLOCTRACE=1``): an allocation ledger recording site,
  thread, and timestamp per alloc; ``Ocm.tini()`` reports leaks.
- :mod:`~oncilla_tpu.analysis.project` — whole-project protocol checks:
  every request :class:`MsgType` has a daemon handler, every type has a
  schema, and every schema survives an encode/decode roundtrip.
- :mod:`~oncilla_tpu.analysis.lockwatch` — a runtime lock-order watchdog
  (``OCM_LOCKWATCH=1``): records the cross-thread lock acquisition-order
  graph, reports cycles (potential deadlocks) and over-threshold holds.
- :mod:`~oncilla_tpu.analysis.conformance` — cross-language wire
  conformance: extracts the full protocol surface from BOTH
  implementations (Python ``protocol.py``/``daemon.py`` and the native
  ``protocol.hh/.cc``/``daemon.cc``), checks enum/schema/flag/dispatch
  parity, fencing completeness, data-tail strip order, and the audit↔
  journal event cross-reference; generates the capability/parity matrix
  in docs/ARCHITECTURE.md with a drift check.
- :mod:`~oncilla_tpu.analysis.asyncsafety` — asyncio lint over the mux
  runtime and everything on its loop: blocking calls inside coroutines,
  locks or thread-local installs held across ``await``, untracked
  ``create_task``.
- :mod:`~oncilla_tpu.analysis.rpcgraph` — the distributed wait-graph
  pass: extracts per-handler outbound RPCs plus the resources held at
  each call site into a typed wait-graph and checks it for relay
  cycles, pool-stratification deadlocks, locks held across peer dials,
  and unbounded network waits on budgeted paths; generates the RPC
  topology appendix in docs/ARCHITECTURE.md with a drift check.
- :mod:`~oncilla_tpu.analysis.waitwatch` — the rpcgraph pass's runtime
  twin (``OCM_WAITWATCH=1``): fuses locks, pool slots, worker-pool
  admission, and RPC round-trips into one wait-for graph, asserted
  acyclic in the stress suites.

CLI: ``python -m oncilla_tpu.analysis`` — exits nonzero on findings not
covered by the checked-in baseline (``analysis_baseline.json``). See
docs/ANALYSIS.md.
"""

from oncilla_tpu.analysis.asyncsafety import scan_async
from oncilla_tpu.analysis.conformance import check_conformance
from oncilla_tpu.analysis.lifecycle import analyze_source, scan_lifecycle
from oncilla_tpu.analysis.lint import Finding, scan_paths
from oncilla_tpu.analysis.project import check_protocol
from oncilla_tpu.analysis.rpcgraph import check_rpcgraph, scan_rpcgraph

__all__ = [
    "Finding", "scan_paths", "check_protocol", "scan_lifecycle",
    "analyze_source", "scan_async", "check_conformance",
    "scan_rpcgraph", "check_rpcgraph",
]
