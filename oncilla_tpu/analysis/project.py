"""Whole-project checks that need the real modules, not just their ASTs.

Protocol exhaustiveness: the wire protocol (runtime/protocol.py) and the
daemon dispatch table (runtime/daemon.py) evolve in different PRs; a
request type added to one but not the other turns into a runtime
``BAD_MSG`` error under load — exactly the class of drift a static gate
should catch at commit time. The roundtrip check packs a synthetic message
of every schema and decodes it back, so a schema whose field formats
disagree with the codec fails here rather than on the wire.
"""

from __future__ import annotations

from oncilla_tpu.analysis.lint import Finding

# Reply/notification suffixes: types a daemon SENDS but never dispatches.
_REPLY_SUFFIXES = ("_OK", "_CONFIRM", "_RESULT", "_PLACED")

_DUMMY = {"q": -3, "Q": 7, "I": 5, "B": 2, "H": 4, "d": 1.5, "s": "héllo"}


def _is_request(name: str) -> bool:
    return not name.endswith(_REPLY_SUFFIXES) and name != "ERROR"


def check_protocol() -> list[Finding]:
    from oncilla_tpu.runtime import daemon, protocol

    findings: list[Finding] = []
    path = "oncilla_tpu/runtime/protocol.py"

    def flag(symbol: str, message: str, where: str = path) -> None:
        findings.append(Finding(
            rule="protocol-exhaustiveness", path=where, line=0,
            symbol=symbol, message=message,
        ))

    schemas = protocol._SCHEMAS
    for t in protocol.MsgType:
        if t not in schemas:
            flag(t.name, f"MsgType.{t.name} has no payload schema")
    handled = set(daemon._HANDLERS)
    for t in protocol.MsgType:
        if _is_request(t.name) and t not in handled:
            flag(
                t.name,
                f"request MsgType.{t.name} has no daemon handler "
                "(_HANDLERS in runtime/daemon.py)",
                where="oncilla_tpu/runtime/daemon.py",
            )

    # Flag exhaustiveness: every header-flag bit the protocol declares
    # valid on a REQUEST type must be claimed as handled by the daemon
    # (_FLAGS_HANDLED) — an unhandled combination would silently degrade
    # (or desync the reply stream) under load instead of failing here.
    # Declared flags must also survive a pack/unpack roundtrip, and
    # undeclared bits must be REJECTED at pack time.
    flags_handled = getattr(daemon, "_FLAGS_HANDLED", {})
    for t, mask in protocol.VALID_FLAGS.items():
        if t not in schemas:
            continue  # already flagged above
        if _is_request(t.name):
            unhandled = mask & ~flags_handled.get(t, 0)
            if unhandled:
                flag(
                    t.name,
                    f"MsgType.{t.name} declares flag bits {unhandled:#x} in "
                    "VALID_FLAGS with no daemon handling "
                    "(_FLAGS_HANDLED in runtime/daemon.py)",
                    where="oncilla_tpu/runtime/daemon.py",
                )
        fields = {name: _DUMMY[fmt] for name, fmt in schemas[t]}
        msg = protocol.Message(t, dict(fields), b"", flags=mask)
        try:
            buf = protocol.pack(msg)
            out = protocol.unpack(
                bytes(buf[: protocol.HEADER.size]),
                bytes(buf[protocol.HEADER.size:]),
            )
        except Exception as e:  # noqa: BLE001 — any codec blowup is a finding
            flag(t.name, f"MsgType.{t.name} flags={mask:#x} roundtrip "
                         f"raised {type(e).__name__}: {e}")
        else:
            if out.flags != mask:
                flag(t.name, f"MsgType.{t.name} flags {mask:#x} not "
                             f"preserved by the codec (got {out.flags:#x})")
        bad_bit = 0x8000  # no capability uses the top bit
        try:
            protocol.pack(protocol.Message(t, dict(fields), b"",
                                           flags=mask | bad_bit))
        except protocol.OcmProtocolError:
            pass
        else:
            flag(t.name, f"MsgType.{t.name} accepted undeclared flag bit "
                         f"{bad_bit:#x} at pack time")
    for t, mask in flags_handled.items():
        extra = mask & ~protocol.VALID_FLAGS.get(t, 0)
        if extra:
            flag(
                t.name,
                f"daemon claims to handle flag bits {extra:#x} on "
                f"MsgType.{t.name} that VALID_FLAGS never declares",
                where="oncilla_tpu/runtime/daemon.py",
            )

    # Encode/decode roundtrip for every schema, with and without a bulk
    # data tail (the codec must keep fields and data separable).
    for t, schema in schemas.items():
        fields = {name: _DUMMY[fmt] for name, fmt in schema}
        for data in (b"", b"\x01\x02\x03"):
            msg = protocol.Message(t, dict(fields), data)
            try:
                buf = protocol.pack(msg)
                out = protocol.unpack(
                    bytes(buf[: protocol.HEADER.size]),
                    bytes(buf[protocol.HEADER.size:]),
                )
            except Exception as e:  # noqa: BLE001 — any codec blowup is a finding
                flag(t.name, f"MsgType.{t.name} roundtrip raised "
                             f"{type(e).__name__}: {e}")
                break
            if out.fields != fields or bytes(out.data) != data:
                flag(t.name, f"MsgType.{t.name} roundtrip mismatch: "
                             f"sent {fields!r}+{data!r}, "
                             f"got {out.fields!r}+{bytes(out.data)!r}")
                break
    return findings
