"""HBM bandwidth ceiling probe — the rerunnable evidence behind the copy
bandwidth headline.

BASELINE.md's transplanted target is 80 % of the v5e chip's 819 GB/s HBM
figure; the bench headline (extent-to-extent arena copies) lands ~0.88 of
that. This module turns the ceiling argument from a docstring claim into a
measurement (VERDICT r3 item 3): a copy's read+write turnaround keeps HBM
below the *read-only* line rate that the 819 figure describes, and no
descriptor scheme recovers it. Three probes, all on the real chip:

1. :func:`hbm_read_gbps` — a read-ONLY stream: the DMA engine pulls HBM
   chunks into a VMEM scratch (on-chip, no HBM write-back), double-buffered.
   HBM sees pure reads, so this approaches the quoted line rate and bounds
   everything else from above.
2. :func:`copy_gbps` — HBM→HBM extent copies with N persistent in-flight
   descriptor streams (the bench's scheme, parameterized to 1/2/4/8): shows
   the plateau is stream-count-independent — the engine saturates, more
   queue depth adds nothing.
3. :func:`vmem_roundtrip_gbps` — the same copy staged through VMEM
   (HBM→VMEM→HBM): strictly worse than the direct descriptor, evidence the
   direct DMA is the right scheme, not a missed optimization.

The measurement *shape* matches the reference's bandwidth harnesses
(size-held, iteration-timed, separate passes —
/root/reference/test/ocm_test.c:362-402); accounting follows the bench: a
copy is credited 2·nbytes of HBM traffic (read + write), the read-only
stream 1·nbytes.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

BLOCK = 4096


def _sync(b) -> None:
    """Force completion (tunnel-proof: readback, not block_until_ready)."""
    np.asarray(jax.device_get(b.reshape(-1)[:8]))


def _fresh(total_bytes: int) -> jax.Array:
    """A freshly transferred buffer (the HBM placement the DMA engine
    sustains best — see core/hbm.py arena materialization note)."""
    return jax.device_put(np.zeros(total_bytes, dtype=np.uint8))


def _interpret():
    from oncilla_tpu.ops.pallas_ici import _interpret_arg, _interpret_mode

    return _interpret_arg(_interpret_mode())


def _read_stream_loop(total_bytes: int, chunk_bytes: int, iters: int):
    """DMA every chunk of the buffer into a 2-deep VMEM scratch ring,
    ``iters`` sweeps, next chunk's descriptor posted before waiting the
    current one (double-buffered — the extoll.c:44-51 scheme)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    assert total_bytes % chunk_bytes == 0 and chunk_bytes % BLOCK == 0
    nchunks = total_bytes // chunk_bytes
    cb = chunk_bytes // BLOCK
    total = iters * nchunks

    def kernel(buf_in, buf_out, scratch, sems):
        del buf_in  # aliased; the kernel only reads buf_out

        def dma(i):
            c = jax.lax.rem(i, nchunks)
            return pltpu.make_async_copy(
                buf_out.at[pl.ds(c * cb, cb)],
                scratch.at[jax.lax.rem(i, 2)],
                sems.at[jax.lax.rem(i, 2)],
            )

        dma(0).start()

        def body(i, _):
            dma(i + 1).start()
            dma(i).wait()
            return 0

        jax.lax.fori_loop(0, total - 1, body, 0)
        dma(total - 1).wait()

    call = pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, cb, 32, 128), jnp.uint8),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        out_shape=jax.ShapeDtypeStruct((total_bytes // BLOCK, 32, 128), jnp.uint8),
        input_output_aliases={0: 0},
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=_interpret(),
    )

    def run(b):
        return call(b.reshape(-1, 32, 128)).reshape(total_bytes)

    return jax.jit(run, donate_argnums=0)


def hbm_read_gbps(
    total_bytes: int = 256 << 20, chunk_bytes: int = 2 << 20, iters: int = 600
) -> float:
    """Read-only HBM stream rate (GB/s of HBM read traffic).

    ``iters`` must put the device time well past the tunnel's dispatch +
    readback latency (~30 ms): 8 sweeps (~2 GiB, ~3 ms of engine time)
    measured the tunnel, not HBM — the r5 first run banked 59.9 GB/s for
    a read-only stream while copies did 579, a physical impossibility.
    600 sweeps ≈ 157 GB ≈ 0.2+ s of engine time, >85 % of the timed
    window on the worst tunnel observed."""
    run = _read_stream_loop(total_bytes, chunk_bytes, iters)
    buf = _fresh(total_bytes)
    buf = run(buf)
    buf = run(buf)  # steady-state layouts after donation
    _sync(buf)
    t0 = time.perf_counter()
    buf = run(buf)
    _sync(buf)
    dt = time.perf_counter() - t0
    return total_bytes * iters / dt / 1e9


def _copy_stream_loop(total_bytes: int, nbytes: int, iters: int, streams: int):
    """N persistent descriptor streams ping-ponging disjoint segment pairs
    (the bench.py scheme, stream count parameterized)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nblocks = nbytes // BLOCK
    assert nblocks % (2 * streams) == 0
    # The ping-pong segment pairs span 2*nbytes of the buffer; anything
    # smaller would emit out-of-bounds DMA descriptors.
    assert total_bytes >= 2 * nbytes, (total_bytes, nbytes)
    q = nblocks // streams

    def kernel(buf_in, buf_out, sems):
        del buf_in

        def dma(stream, i):
            fwd = i % 2 == 0
            base = stream * 2 * q
            src = base + jnp.where(fwd, 0, q)
            dst = base + jnp.where(fwd, q, 0)
            return pltpu.make_async_copy(
                buf_out.at[pl.ds(src, q)],
                buf_out.at[pl.ds(dst, q)],
                sems.at[stream],
            )

        for s in range(streams):
            dma(s, 0).start()

        def body(i, _):
            for s in range(streams):
                dma(s, i).wait()
                dma(s, i + 1).start()
            return 0

        jax.lax.fori_loop(0, iters - 1, body, 0)
        for s in range(streams):
            dma(s, iters - 1).wait()

    call = pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((streams,))],
        out_shape=jax.ShapeDtypeStruct((total_bytes // BLOCK, 32, 128), jnp.uint8),
        input_output_aliases={0: 0},
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=_interpret(),
    )

    def run(b):
        return call(b.reshape(-1, 32, 128)).reshape(total_bytes)

    return jax.jit(run, donate_argnums=0)


def copy_gbps(
    streams: int,
    total_bytes: int = 128 << 20,
    nbytes: int = 64 << 20,
    iters: int = 2000,
) -> float:
    """HBM→HBM copy traffic (2·nbytes per iteration) with ``streams``
    persistent in-flight descriptors. 2000 iterations matches the bench
    headline loop: at 500 the ~30 ms tunnel sync was ~20 % of the timed
    window and the sweep under-read the engine by ~25 % (455 vs 579 in
    the r5 first run)."""
    run = _copy_stream_loop(total_bytes, nbytes, iters, streams)
    buf = _fresh(total_bytes)
    buf = run(buf)
    buf = run(buf)
    _sync(buf)
    t0 = time.perf_counter()
    buf = run(buf)
    _sync(buf)
    dt = time.perf_counter() - t0
    return 2.0 * nbytes * iters / dt / 1e9


def _vmem_roundtrip_loop(total_bytes: int, nbytes: int, iters: int,
                         chunk_bytes: int = 2 << 20):
    """The same ping-pong extent copy, but every chunk staged HBM→VMEM→HBM
    (two DMA hops per byte)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nblocks = nbytes // BLOCK
    cb = chunk_bytes // BLOCK
    assert nblocks % (2 * cb) == 0
    assert total_bytes >= 2 * nbytes, (total_bytes, nbytes)
    nchunks = nblocks // cb

    def kernel(buf_in, buf_out, scratch, sems):
        del buf_in

        def leg(i, c):
            """Chunk c of iteration i: in HBM→VMEM, then VMEM→HBM."""
            fwd = i % 2 == 0
            src = jnp.where(fwd, 0, nblocks) + c * cb
            dst = jnp.where(fwd, nblocks, 0) + c * cb
            slot = jax.lax.rem(c, 2)
            down = pltpu.make_async_copy(
                buf_out.at[pl.ds(src, cb)], scratch.at[slot], sems.at[slot]
            )
            up = pltpu.make_async_copy(
                scratch.at[slot], buf_out.at[pl.ds(dst, cb)], sems.at[2 + slot]
            )
            return down, up

        def body(i, _):
            def chunk_body(c, _):
                down, up = leg(i, c)
                down.start()
                down.wait()
                up.start()
                up.wait()
                return 0

            return jax.lax.fori_loop(0, nchunks, chunk_body, 0)

        jax.lax.fori_loop(0, iters, body, 0)

    call = pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, cb, 32, 128), jnp.uint8),
            pltpu.SemaphoreType.DMA((4,)),
        ],
        out_shape=jax.ShapeDtypeStruct((total_bytes // BLOCK, 32, 128), jnp.uint8),
        input_output_aliases={0: 0},
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=_interpret(),
    )

    def run(b):
        return call(b.reshape(-1, 32, 128)).reshape(total_bytes)

    return jax.jit(run, donate_argnums=0)


def vmem_roundtrip_gbps(
    total_bytes: int = 128 << 20, nbytes: int = 64 << 20, iters: int = 400,
    chunk_bytes: int = 2 << 20,
) -> float:
    """Copy traffic (2·nbytes per iteration of HBM read+write) when staged
    through VMEM."""
    run = _vmem_roundtrip_loop(total_bytes, nbytes, iters, chunk_bytes)
    buf = _fresh(total_bytes)
    buf = run(buf)
    buf = run(buf)
    _sync(buf)
    t0 = time.perf_counter()
    buf = run(buf)
    _sync(buf)
    dt = time.perf_counter() - t0
    return 2.0 * nbytes * iters / dt / 1e9


def ceiling_probe(deadline: float | None = None) -> dict:
    """All three probes; with ``deadline`` (time.monotonic()), later stages
    are skipped (marked -1) once it passes — partial evidence beats none."""
    out: dict = {}

    def left() -> float:
        return float("inf") if deadline is None else deadline - time.monotonic()

    out["read_only_gbps"] = round(hbm_read_gbps(), 2)
    out["copy_streams_gbps"] = {}
    for s in (1, 2, 4, 8):
        if left() < 45:
            out["copy_streams_gbps"][str(s)] = -1.0
            continue
        out["copy_streams_gbps"][str(s)] = round(copy_gbps(s), 2)
    out["vmem_roundtrip_gbps"] = (
        round(vmem_roundtrip_gbps(), 2) if left() >= 45 else -1.0
    )
    return out


def main() -> None:
    import json

    print(json.dumps(ceiling_probe()))


if __name__ == "__main__":
    main()
