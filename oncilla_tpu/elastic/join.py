"""Joiner/leaver side of the elastic membership protocol.

:func:`join_cluster` is what a fresh daemon process runs instead of the
boot-time nodefile path: bind a listener FIRST (peers dialing the freshly
announced rank queue in the backlog instead of bouncing off a closed
port), dial rank 0 with REQ_JOIN, and build the daemon from the JOIN_OK
grant — assigned rank, cluster epoch, and the full member table. The
request retries with capped backoff: a dropped REQ_JOIN or a lost
JOIN_OK re-sends idempotently, and rank 0 dedups the (host, port)
announcement onto the original rank, so a retried join can never leak a
half-member slot.

:func:`leave_cluster` is the graceful departure: REQ_LEAVE asks rank 0
to drain everything the leaver holds (migrate primaries out, re-home
replica copies), and only a COMPLETE drain lets the member depart —
rank 0 bumps the epoch, broadcasts the shrunk view, and the leaver stops
serving. A refused drain leaves the member in place; dying instead is
the *unclean* path and degrades to the DEAD-verdict failover ladder.
"""

from __future__ import annotations

import os
import socket
import time

from oncilla_tpu.core.errors import OcmConnectError, OcmError, OcmRemoteError
from oncilla_tpu.runtime.membership import ClusterView, NodeEntry
from oncilla_tpu.runtime.pool import PeerPool
from oncilla_tpu.runtime.protocol import ErrCode, Message, MsgType
from oncilla_tpu.utils.config import OcmConfig
from oncilla_tpu.utils.debug import printd


def join_cluster(
    rank0_host: str,
    rank0_port: int,
    config: OcmConfig | None = None,
    *,
    host: str = "127.0.0.1",
    port: int = 0,
    advertise_host: str | None = None,
    policy: str = "capacity",
    ndevices: int = 1,
    snapshot_path: str | None = None,
    retries: int = 20,
):
    """Join a running cluster and return the STARTED joiner daemon.

    The listener binds (and listens) before REQ_JOIN goes out, so the
    instant rank 0 broadcasts the new member, peer dials land in the
    backlog and are served the moment :meth:`Daemon.start` runs the
    accept loop. ``advertise_host`` is the address peers should dial
    (defaults to the bind host — pass it when binding a wildcard).
    """
    from oncilla_tpu.runtime.daemon import Daemon  # cycle: daemon imports elastic

    config = config or OcmConfig()
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        listener.bind((host, port))
        listener.listen(64)
        port = listener.getsockname()[1]
        inc = int.from_bytes(os.urandom(8), "little") or 1
        req = Message(
            MsgType.REQ_JOIN,
            {
                "host": advertise_host or host,
                "port": port,
                "ndevices": ndevices,
                "device_arena_bytes": config.device_arena_bytes,
                "host_arena_bytes": config.host_arena_bytes,
                "inc": inc,
            },
        )
        # A short-lived pool (not a bare socket) so the chaos harness's
        # lease seam covers the JOIN leg too — a partitioned or dropped
        # REQ_JOIN retries idempotently, which IS the protocol claim the
        # smoke proves.
        pool = PeerPool()
        seed = (rank0_host, rank0_port)
        try:
            reply = None
            for i in range(retries):
                try:
                    reply = pool.request(seed[0], seed[1], req)
                    break
                except OcmRemoteError as e:
                    # Leadership moved off the seed (control/): the
                    # NOT_MASTER redirect names the live leader's
                    # address explicitly — a joiner has no member table
                    # yet, so the rank alone would be useless.
                    addr = getattr(e, "leader_addr", None)
                    if e.code == int(ErrCode.NOT_MASTER) and addr:
                        printd("join: seed %s:%d is not the leader; "
                               "redirected to %s:%d",
                               seed[0], seed[1], addr[0], addr[1])
                        seed = tuple(addr)
                        continue
                    raise
                except (OSError, OcmConnectError) as e:
                    printd("join: REQ_JOIN attempt %d failed: %s", i, e)
                    time.sleep(min(0.05 * 2 ** i, 2.0))
            if reply is None:
                raise OcmConnectError(
                    f"leader unreachable at {seed[0]}:{seed[1]} "
                    f"after {retries} REQ_JOIN attempts"
                )
        finally:
            pool.close()
        rank = reply.fields["rank"]
        epoch = reply.fields["epoch"]
        view = ClusterView([])
        if not reply.data:
            raise OcmError("JOIN_OK carried no member table")
        view.adopt(epoch, bytes(reply.data))
        if not (0 <= rank < len(view)):
            raise OcmError(
                f"JOIN_OK rank {rank} not in the granted member table"
            )
        d = Daemon(
            rank, view, config=config, policy=policy, ndevices=ndevices,
            host=host, snapshot_path=snapshot_path,
            incarnation=inc, listener=listener,
        )
        listener = None  # owned by the daemon now
        # The daemon that granted JOIN_OK IS the leader (only leaders
        # admit): seed leader_rank from the address that answered, so a
        # joiner admitted after a leadership transfer aims its ADD_NODE
        # and proxies at the live leader instead of bouncing off rank 0.
        lead = view.find(seed[0], seed[1])
        if lead is not None:
            d.leader_rank = lead
        d._adopt_epoch(epoch)
        d.start()
        # The granted view may name members a boot-time constructor never
        # saw (and departed ones it must not probe).
        d._reconcile_detector()
        printd("join: rank %d serving at %s:%d (epoch %d, %d members)",
               rank, host, port, epoch, view.alive_count())
        return d
    finally:
        if listener is not None:
            listener.close()


def leave_cluster(daemon, retries: int = 3) -> dict:
    """Gracefully depart: drain-then-drop via the leader, then stop
    serving.

    A daemon that currently LEADS first hands the role off to the
    lowest live standby (``Daemon.handoff_leadership`` — final master
    state pushed synchronously under the CRC discipline), then departs
    as an ordinary member through the successor. This closes the
    "rank 0 cannot leave" hole noted in PR 8; without standby masters
    configured there is nobody to hand to and the leader still refuses.

    Returns ``{"epoch": ..., "moved": ...}`` from LEAVE_OK. Raises (and
    leaves the daemon RUNNING) if the leader refuses — e.g. the drain
    could not complete, or this daemon's incarnation no longer matches
    the member table (a restarted daemon at the same address must
    re-join before it may leave).
    """
    if daemon.rank == daemon.leader_rank:
        if daemon.config.standby_masters <= 0:
            raise OcmError(
                f"rank {daemon.rank} leads the cluster and cannot leave: "
                "no standby masters configured (OCM_STANDBY_MASTERS)"
            )
        daemon.handoff_leadership()
    req = Message(
        MsgType.REQ_LEAVE,
        {"rank": daemon.rank, "inc": daemon.incarnation},
    )
    last: Exception | None = None
    for i in range(retries):
        le = daemon._leader_entry()
        try:
            reply = daemon.peers.request(le.connect_host, le.port, req)
            break
        except OcmRemoteError as e:
            if e.code == int(ErrCode.NOT_MASTER) and getattr(
                e, "leader_rank", None
            ) is not None:
                daemon._adopt_leader_hint(e)
                last = e
                continue
            # A typed refusal (drain incomplete, stale incarnation) is
            # the caller's problem, not noise.
            raise
        except (OSError, OcmConnectError) as e:
            last = e
            time.sleep(min(0.05 * 2 ** i, 1.0))
    else:
        raise OcmRemoteError(
            0, f"leader unreachable for REQ_LEAVE: {last}"
        )
    out = {"epoch": reply.fields["epoch"], "moved": reply.fields["moved"]}
    printd("leave: rank %d departed at epoch %d (%d extents moved)",
           daemon.rank, out["epoch"], out["moved"])
    daemon.stop()
    return out
