"""SLO engine: declarative objectives + multi-window burn-rate alerts.

The prom families say what happened; this module says whether that was
GOOD ENOUGH. Objectives are declarative records (:class:`Objective`)
evaluated over the in-process metrics history
(:mod:`~oncilla_tpu.obs.scrape`), in three shapes:

* ``latency`` — the fraction of windowed histogram observations under a
  threshold must meet a target. The default ladder expresses each QoS
  priority class's bound as a *fraction of the deadline budget*
  (``OCM_DEADLINE_MS``): high priority gets half the budget, normal the
  budget, low twice it — so tightening the budget tightens every
  objective with no spec edit. Serving TTFT rides the same shape over
  ``ocm_serving_ttft_seconds``.
* ``availability`` — typed error counters (``BUSY`` backpressure,
  ``DEADLINE_EXCEEDED``, client breaker opens) as a fraction of
  ``ocm_op_total`` must stay under ``1 - target``.
* ``throughput`` — a counter's windowed rate (serving decode
  tokens/sec) must clear a floor while the stream is active.

Alerting is the SRE-workbook multi-window burn rate: per objective the
error ratio is turned into ``burn = error_ratio / (1 - target)`` over a
fast and a slow window, and the objective only trips when BOTH exceed
the threshold — the fast window for reaction time, the slow one so a
single bad scrape can't page. Verdicts publish three ways: ``ocm_slo_*``
prom families (:func:`SloEngine.render_prom`), ``slo_burn``/``slo_ok``
journal events, and the ``obs slo`` CLI table.

``OCM_SLO`` selects the spec: unset/empty = defaults, ``0``/``off`` =
disabled, inline JSON or a path to a JSON file = custom objectives.
Parsing is tolerant — a malformed spec degrades to the defaults rather
than crashing the host process.

Stdlib-only by the obs-package contract.
"""

from __future__ import annotations

import json
import os
import threading
import time

from oncilla_tpu.obs import journal, prom, scrape

ENV_SLO = "OCM_SLO"

# Default windows/threshold are sized for an in-process watcher, not a
# paging pipeline: minutes, not hours. Spec files can override all three.
DEFAULT_FAST_S = 60.0
DEFAULT_SLOW_S = 300.0
DEFAULT_BURN_THRESHOLD = 2.0
# When OCM_DEADLINE_MS is unset (0 = no deadline discipline) the latency
# ladder still needs an anchor; one second is the repo's chaos-smoke
# scale.
DEFAULT_BUDGET_S = 1.0


class Objective:
    """One declarative objective. ``match`` pins exposition labels
    (subset match); ``kind`` picks the evaluation shape."""

    def __init__(
        self,
        name: str,
        kind: str,
        *,
        family: str = "",
        target: float = 0.99,
        threshold_s: float = 0.0,
        min_rate: float = 0.0,
        errors: list[tuple[str, dict]] | None = None,
        total_family: str = "",
        match: dict | None = None,
        priority: str = "",
    ) -> None:
        if kind not in ("latency", "availability", "throughput"):
            raise ValueError(f"unknown objective kind {kind!r}")
        self.name = name
        self.kind = kind
        self.family = family
        self.target = float(target)
        self.threshold_s = float(threshold_s)
        self.min_rate = float(min_rate)
        self.errors = errors or []
        self.total_family = total_family
        self.match = dict(match or {})
        self.priority = priority

    @classmethod
    def from_dict(cls, d: dict) -> "Objective":
        errs = [
            (e["family"], dict(e.get("match", {})))
            for e in d.get("errors", [])
        ]
        return cls(
            d["name"],
            d["kind"],
            family=d.get("family", ""),
            target=d.get("target", 0.99),
            threshold_s=d.get("threshold_s", 0.0),
            min_rate=d.get("min_rate", 0.0),
            errors=errs,
            total_family=d.get("total_family", ""),
            match=d.get("match"),
            priority=str(d.get("priority", "")),
        )


def default_objectives(budget_s: float | None = None) -> list[Objective]:
    """The built-in objective set. The latency ladder is the QoS
    priority classes (utils/config.py: 0 low, 1 normal, 2 high), each
    bounded by a fraction of the deadline budget."""
    if budget_s is None:
        try:
            ms = int(os.environ.get("OCM_DEADLINE_MS", "") or 0)
        except ValueError:
            ms = 0
        budget_s = (ms / 1000.0) if ms > 0 else DEFAULT_BUDGET_S
    out = [
        Objective(
            f"latency_{cls}", "latency",
            family="ocm_op_latency_seconds",
            threshold_s=frac * budget_s, target=target, priority=cls,
        )
        for cls, frac, target in (
            ("high", 0.5, 0.99),
            ("normal", 1.0, 0.99),
            ("low", 2.0, 0.95),
        )
    ]
    out.append(Objective(
        "availability", "availability",
        errors=[
            ("ocm_backpressure_busy_total", {}),
            ("ocm_deadline_exceeded_total", {}),
            ("ocm_client_breaker_opens_total", {}),
        ],
        total_family="ocm_op_total",
        target=0.999,
    ))
    out.append(Objective(
        "serving_ttft", "latency",
        family="ocm_serving_ttft_seconds",
        threshold_s=budget_s, target=0.95, priority="serving",
    ))
    out.append(Objective(
        "serving_tokens", "throughput",
        family="ocm_serving_tokens_total",
        match={"phase": "decode"},
        min_rate=1.0, target=0.99,
    ))
    return out


def load_spec(
    budget_s: float | None = None,
) -> tuple[list[Objective], float, float, float] | None:
    """Resolve ``OCM_SLO`` into ``(objectives, fast_s, slow_s,
    burn_threshold)``; ``None`` means the engine is disabled."""
    raw = (os.environ.get(ENV_SLO, "") or "").strip()
    if raw.lower() in ("0", "off", "false"):
        return None
    fast, slow, thr = DEFAULT_FAST_S, DEFAULT_SLOW_S, DEFAULT_BURN_THRESHOLD
    if raw in ("", "1", "on", "true"):
        return default_objectives(budget_s), fast, slow, thr
    text = raw
    if raw.startswith("@") or os.path.exists(raw):
        try:
            with open(raw.lstrip("@"), encoding="utf-8") as fh:
                text = fh.read()
        except OSError:
            return default_objectives(budget_s), fast, slow, thr
    try:
        spec = json.loads(text)
        objectives = [
            Objective.from_dict(d) for d in spec.get("objectives", [])
        ] or default_objectives(budget_s)
        fast = float(spec.get("fast_s", fast))
        slow = float(spec.get("slow_s", slow))
        thr = float(spec.get("burn_threshold", thr))
    except (ValueError, KeyError, TypeError, AttributeError):
        # Malformed spec: degrade to the defaults (the same stance as
        # the env-knob parsers) — a typo'd SLO file must not take down
        # the process it was meant to watch.
        return default_objectives(budget_s), fast, slow, thr
    return objectives, fast, slow, thr


def _latency_error_ratio(
    hist: scrape.MetricsHistory,
    obj: Objective,
    window_s: float,
    now: float,
) -> tuple[float, float]:
    """(fraction of windowed observations OVER the threshold, count)."""
    by_le = hist.hist_deltas(obj.family, window_s, now=now, **obj.match)
    if not by_le:
        return 0.0, 0.0
    total = by_le.get(float("inf"), max(by_le.values()))
    if total <= 0:
        return 0.0, 0.0
    # Cumulative count at the threshold, linearly interpolated inside
    # the straddling bucket (same estimator as hist_quantile, inverted).
    prev_le, prev_cum = 0.0, 0.0
    good = total
    for le in sorted(by_le):
        cum = by_le[le]
        if le >= obj.threshold_s:
            if le == float("inf") or le == prev_le:
                good = prev_cum if obj.threshold_s > prev_le else cum
            else:
                frac = (obj.threshold_s - prev_le) / (le - prev_le)
                good = prev_cum + frac * (cum - prev_cum)
            break
        prev_le, prev_cum = le, cum
    return max(0.0, min(1.0, 1.0 - good / total)), total


class SloEngine:
    """Evaluates objectives over a :class:`MetricsHistory` and carries
    the verdict state (for burn/ok transition events and the prom
    rendering)."""

    def __init__(
        self,
        history: scrape.MetricsHistory,
        objectives: list[Objective] | None = None,
        *,
        fast_s: float = DEFAULT_FAST_S,
        slow_s: float = DEFAULT_SLOW_S,
        burn_threshold: float = DEFAULT_BURN_THRESHOLD,
    ) -> None:
        self.history = history
        self.objectives = (
            objectives if objectives is not None else default_objectives()
        )
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)
        self.burn_threshold = float(burn_threshold)
        self._mu = threading.Lock()
        self._burning: set[str] = set()
        self._last: dict | None = None
        self.evaluations = 0

    # -- evaluation -----------------------------------------------------

    def _error_ratio(
        self, obj: Objective, window_s: float, now: float
    ) -> tuple[float, float]:
        """(error ratio in [0,1], activity count) for one window."""
        if obj.kind == "latency":
            return _latency_error_ratio(self.history, obj, window_s, now)
        if obj.kind == "availability":
            total = self.history.delta(
                obj.total_family, window_s, now=now, **obj.match
            )
            if total <= 0:
                return 0.0, 0.0
            errs = sum(
                self.history.delta(fam, window_s, now=now, **m)
                for fam, m in obj.errors
            )
            return max(0.0, min(1.0, errs / total)), total
        # throughput: binary violation while the stream is active. An
        # idle stream is "no activity", not a breach — a serving engine
        # that was never started must not page.
        rate = self.history.rate(obj.family, window_s, now=now, **obj.match)
        delta = self.history.delta(obj.family, window_s, now=now, **obj.match)
        if delta <= 0 and rate <= 0:
            return 0.0, 0.0
        return (1.0 if rate < obj.min_rate else 0.0), max(delta, 1.0)

    def evaluate(self, now: float | None = None) -> dict:
        """One evaluation sweep. Returns (and retains, for
        :meth:`meta`/:meth:`render_prom`) the verdict dict; records
        ``slo_burn`` events while an objective burns and one ``slo_ok``
        on each recovery transition."""
        now = time.time() if now is None else now
        verdicts = []
        for obj in self.objectives:
            fast_err, fast_n = self._error_ratio(obj, self.fast_s, now)
            slow_err, slow_n = self._error_ratio(obj, self.slow_s, now)
            denom = max(1.0 - obj.target, 1e-9)
            burn_fast = fast_err / denom
            burn_slow = slow_err / denom
            active = fast_n > 0 or slow_n > 0
            burning = (
                active
                and burn_fast > self.burn_threshold
                and burn_slow > self.burn_threshold
            )
            verdicts.append({
                "objective": obj.name,
                "kind": obj.kind,
                "priority": obj.priority,
                "target": obj.target,
                "threshold_s": obj.threshold_s,
                "min_rate": obj.min_rate,
                "ok": not burning,
                "active": active,
                "burn_fast": round(burn_fast, 4),
                "burn_slow": round(burn_slow, 4),
                "error_fast": round(fast_err, 6),
                "error_slow": round(slow_err, 6),
                "n_fast": fast_n,
            })
        result = {
            "ok": all(v["ok"] for v in verdicts),
            "ts": now,
            "fast_s": self.fast_s,
            "slow_s": self.slow_s,
            "burn_threshold": self.burn_threshold,
            "objectives": verdicts,
        }
        with self._mu:
            self.evaluations += 1
            was_burning = set(self._burning)
            self._burning = {
                v["objective"] for v in verdicts if not v["ok"]
            }
            self._last = result
        for v in verdicts:
            if not v["ok"]:
                journal.record(
                    "slo_burn", objective=v["objective"],
                    burn_fast=v["burn_fast"], burn_slow=v["burn_slow"],
                    target=v["target"],
                )
            elif v["objective"] in was_burning:
                journal.record(
                    "slo_ok", objective=v["objective"],
                    burn_fast=v["burn_fast"], burn_slow=v["burn_slow"],
                )
        return result

    def meta(self) -> dict:
        """Last verdict + history stats — the ``status()["slo"]`` block."""
        with self._mu:
            last = self._last
        out = {
            "history": self.history.meta(),
            "evaluations": self.evaluations,
        }
        if last is not None:
            out.update(last)
        return out

    # -- exposition -----------------------------------------------------

    def render_prom(self, rank: int = 0) -> str:
        """The ``ocm_slo_*`` families for the last evaluation (runs one
        if none has happened yet); validates against
        :func:`prom.validate` like every other renderer."""
        with self._mu:
            last = self._last
        if last is None:
            last = self.evaluate()
        doc = prom._Doc()
        for v in last["objectives"]:
            doc.sample("ocm_slo_ok", "gauge",
                       "1 while an objective meets its SLO (multi-window "
                       "burn-rate verdict), 0 while it burns.",
                       int(v["ok"]), rank=rank, objective=v["objective"])
            doc.sample("ocm_slo_target", "gauge",
                       "Declared objective target (good fraction).",
                       v["target"], rank=rank, objective=v["objective"])
            for window, burn, err in (
                ("fast", v["burn_fast"], v["error_fast"]),
                ("slow", v["burn_slow"], v["error_slow"]),
            ):
                doc.sample("ocm_slo_burn_rate", "gauge",
                           "Error-budget burn rate per evaluation window "
                           "(error_ratio / (1 - target)); the alert "
                           "requires BOTH windows over the threshold.",
                           burn, rank=rank, objective=v["objective"],
                           window=window)
                doc.sample("ocm_slo_error_ratio", "gauge",
                           "Raw windowed error ratio per objective.",
                           err, rank=rank, objective=v["objective"],
                           window=window)
        doc.sample("ocm_slo_evaluations_total", "counter",
                   "SLO evaluation sweeps run by this engine.",
                   self.evaluations, rank=rank)
        return doc.text()


class SloRunner:
    """The deployable unit: a scraper feeding a history feeding an
    engine, ticked by one background thread. ``extra_samples`` lets the
    host inject client-local counters the daemons cannot see (the
    circuit breaker lives client-side) as synthetic families on every
    tick."""

    def __init__(
        self,
        fetch,
        ranks,
        *,
        objectives: list[Objective] | None = None,
        interval_s: float | None = None,
        fast_s: float = DEFAULT_FAST_S,
        slow_s: float = DEFAULT_SLOW_S,
        burn_threshold: float = DEFAULT_BURN_THRESHOLD,
        extra_samples=None,
        history: scrape.MetricsHistory | None = None,
    ) -> None:
        self.history = history if history is not None else scrape.MetricsHistory()
        self.scraper = scrape.Scraper(
            fetch, ranks, history=self.history, interval_s=interval_s
        )
        self.engine = SloEngine(
            self.history, objectives,
            fast_s=fast_s, slow_s=slow_s, burn_threshold=burn_threshold,
        )
        self.extra_samples = extra_samples
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @classmethod
    def from_env(cls, fetch, ranks, *, interval_s=None,
                 budget_s: float | None = None, extra_samples=None):
        """Build from ``OCM_SLO``; ``None`` when the knob disables it."""
        spec = load_spec(budget_s)
        if spec is None:
            return None
        objectives, fast_s, slow_s, thr = spec
        return cls(
            fetch, ranks, objectives=objectives, interval_s=interval_s,
            fast_s=fast_s, slow_s=slow_s, burn_threshold=thr,
            extra_samples=extra_samples,
        )

    def tick(self, ts: float | None = None) -> dict:
        self.scraper.poll_once(ts=ts)
        if self.extra_samples is not None:
            try:
                extra = self.extra_samples()
            except Exception:
                extra = None
            if extra:
                self.history.observe_samples(extra, ts=ts)
        return self.engine.evaluate(now=ts)

    def start(self) -> "SloRunner":
        if self._thread is not None:
            return self
        self._stop.clear()

        def _loop() -> None:
            while not self._stop.wait(self.scraper.interval_s):
                try:
                    self.tick()
                except Exception:
                    self.history.note_error()

        self._thread = threading.Thread(
            target=_loop, name="ocm-slo", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def meta(self) -> dict:
        return self.engine.meta()
