"""Critical-path latency attribution over merged journal spans.

The exporter (:mod:`~oncilla_tpu.obs.export`) draws traces; this module
answers the operator question the drawing only hints at: *where did the
p99 go?* Input is any merged event stream (in-memory ring, STATUS_EVENTS
pulls, flight-recorder segments, JSONL dumps); spans sharing a
``trace_id`` are joined into op trees on ``parent_span_id`` — exactly
the Dapper parentage the wire protocol already propagates — and each
tree's wall time is decomposed:

* every span's **self time** is its duration minus the union of its
  children's intervals (children clamped into the parent to absorb
  cross-host clock skew);
* ``phase`` journal events (``journal.phase``) carve named slices out
  of the span they bind to — client queue, mux in-flight window wait,
  daemon dispatch queue, replica fan-out, KV residency, the fused jit
  step;
* whatever self time no phase claims is attributed to the span's own op
  name (the handler actually doing the work), so 100% of a tree's wall
  time lands on a *named* phase — "unattributed" is a bug in this
  module, not an expected row.

The **critical path** per tree is the classic backward sweep: from the
root's end, repeatedly step into the latest-ending child overlapping
the cursor; time not covered by any child on that walk is the owning
span's on-path self time. ``obs critpath`` prints both views: the
per-tree path for the slowest ops, and a per-(op, priority) table of
p50/p99 seconds per phase across all trees.

Stdlib-only by the obs-package contract.
"""

from __future__ import annotations

import os

from oncilla_tpu.obs import export, flightrec, journal


# -- loading ------------------------------------------------------------


def load_events(sources: list[str]) -> list[dict]:
    """Events from any mix of flight-recorder directories, ``.seg``
    files, and JSONL journal dumps, merged and (jid, seq)-deduped."""
    streams: list[list[dict]] = []
    for src in sources:
        if os.path.isdir(src):
            evts, _issues = flightrec.read_dir(src)
            streams.append(evts)
        elif src.endswith(".seg"):
            evts, _issues = flightrec.read_segment(src)
            streams.append(evts)
        else:
            streams.append(journal.load_jsonl(src))
    return export.merge(*streams)


# -- tree assembly ------------------------------------------------------


class _Node:
    __slots__ = ("e", "children", "phases")

    def __init__(self, e: dict):
        self.e = e
        self.children: list[_Node] = []
        self.phases: list[dict] = []


def _interval(e: dict) -> tuple[float, float]:
    t0 = float(e.get("t_wall") or e.get("ts", 0.0))
    return t0, t0 + float(e.get("dur_us", 0.0)) / 1e6


def _clamp(t0: float, t1: float, lo: float, hi: float) -> tuple[float, float]:
    t0 = min(max(t0, lo), hi)
    t1 = min(max(t1, t0), hi)
    return t0, t1


def _union_len(ivals: list[tuple[float, float]]) -> float:
    total, cur0, cur1 = 0.0, None, None
    for a, b in sorted(ivals):
        if cur1 is None or a > cur1:
            if cur1 is not None:
                total += cur1 - cur0
            cur0, cur1 = a, b
        else:
            cur1 = max(cur1, b)
    if cur1 is not None:
        total += cur1 - cur0
    return total


def assemble(events: list[dict]) -> list[dict]:
    """Join spans into op trees and decompose each tree's wall time.

    Returns one dict per tree (roots = spans whose parent is absent
    from the stream), largest wall time first:
    ``{trace_id, root_op, priority, wall_s, n_spans, tracks,
    attribution: {phase: seconds}, attributed_frac,
    critical_path: [(op, seconds), ...]}``."""
    nodes: dict[tuple[int, int], _Node] = {}
    for e in events:
        if e.get("ev") == "span" and e.get("trace_id") and e.get("span_id"):
            nodes[(e["trace_id"], e["span_id"])] = _Node(e)
    roots: list[_Node] = []
    for key, node in nodes.items():
        parent = nodes.get((key[0], node.e.get("parent_span_id") or 0))
        if parent is not None and parent is not node:
            parent.children.append(node)
        else:
            roots.append(node)
    for e in events:
        if e.get("ev") == "phase":
            node = nodes.get((e.get("trace_id", 0), e.get("span_id", 0)))
            if node is not None:
                node.phases.append(e)

    trees = []
    for root in roots:
        t0, t1 = _interval(root.e)
        if t1 <= t0:
            continue
        attribution: dict[str, float] = {}
        tracks: set[str] = set()
        priorities: set[str] = set()
        n_spans = 0

        def walk(node: _Node, lo: float, hi: float) -> tuple[float, float]:
            nonlocal n_spans
            n_spans += 1
            tracks.add(str(node.e.get("track") or f"pid{node.e.get('pid', 0)}"))
            for src in (node.e, *(p for p in node.phases)):
                if src.get("priority") not in (None, ""):
                    priorities.add(str(src["priority"]))
            s0, s1 = _clamp(*_interval(node.e), lo, hi)
            kid_ivals = [walk(k, s0, s1) for k in node.children]
            self_s = max(0.0, (s1 - s0) - _union_len(kid_ivals))
            named = 0.0
            for p in node.phases:
                named += float(p.get("dur_us", 0.0)) / 1e6
            # Phases bound to this span can only describe its SELF time;
            # when marks overlap a child (or each other) scale them down
            # rather than invent time the span does not own.
            scale = min(1.0, self_s / named) if named > 0 else 0.0
            for p in node.phases:
                name = str(p.get("phase", "?"))
                attribution[name] = attribution.get(name, 0.0) + (
                    float(p.get("dur_us", 0.0)) / 1e6 * scale
                )
            own = self_s - named * scale
            if own > 0:
                op = str(node.e.get("op", "?"))
                attribution[op] = attribution.get(op, 0.0) + own
            return s0, s1

        walk(root, t0, t1)

        # Backward critical-path sweep.
        path: dict[str, float] = {}

        def sweep(node: _Node, lo: float, hi: float) -> None:
            kids = []
            for k in node.children:
                k0, k1 = _clamp(*_interval(k.e), lo, hi)
                if k1 > k0:
                    kids.append((k1, k0, k))
            cur = hi
            op = str(node.e.get("op", "?"))
            for k1, k0, kid in sorted(kids, reverse=True):
                if cur <= lo:
                    break
                if min(k1, cur) <= lo:
                    continue
                if k1 < cur:
                    path[op] = path.get(op, 0.0) + (cur - k1)
                sweep(kid, k0, min(k1, cur))
                cur = min(cur, k0)
            if cur > lo:
                path[op] = path.get(op, 0.0) + (cur - lo)

        sweep(root, t0, t1)

        wall = t1 - t0
        attributed = sum(attribution.values())
        trees.append({
            "trace_id": root.e.get("trace_id", 0),
            "root_op": str(root.e.get("op", "?")),
            "priority": sorted(priorities)[0] if priorities else "-",
            "wall_s": wall,
            "n_spans": n_spans,
            "tracks": sorted(tracks),
            "attribution": dict(
                sorted(attribution.items(), key=lambda kv: -kv[1])
            ),
            "attributed_frac": min(1.0, attributed / wall) if wall else 0.0,
            "critical_path": sorted(path.items(), key=lambda kv: -kv[1]),
        })
    trees.sort(key=lambda t: -t["wall_s"])
    return trees


# -- aggregation --------------------------------------------------------


def _pct(vals: list[float], q: float) -> float:
    if not vals:
        return 0.0
    vals = sorted(vals)
    i = min(len(vals) - 1, int(round(q * (len(vals) - 1))))
    return vals[i]


def phase_table(trees: list[dict]) -> list[dict]:
    """Per-(root op, priority, phase) aggregate across trees: count,
    p50/p99 of per-tree phase seconds, and the phase's share of the
    group's total attributed time."""
    groups: dict[tuple[str, str], dict[str, list[float]]] = {}
    for t in trees:
        g = groups.setdefault((t["root_op"], t["priority"]), {})
        for phase, secs in t["attribution"].items():
            g.setdefault(phase, []).append(secs)
    rows = []
    for (op, prio), phases in sorted(groups.items()):
        total = sum(sum(v) for v in phases.values()) or 1.0
        for phase, vals in sorted(
            phases.items(), key=lambda kv: -sum(kv[1])
        ):
            rows.append({
                "op": op, "priority": prio, "phase": phase,
                "n": len(vals),
                "p50_s": _pct(vals, 0.50),
                "p99_s": _pct(vals, 0.99),
                "share": sum(vals) / total,
            })
    return rows


def render_report(trees: list[dict], top: int = 3) -> str:
    """The ``obs critpath`` text report: summary line, the slowest
    trees' critical paths, then the phase-attribution table."""
    if not trees:
        return "no op trees (need span events with trace ids)\n"
    cross = sum(1 for t in trees if len(t["tracks"]) > 1)
    lines = [
        f"{len(trees)} op tree(s), {cross} cross-rank, "
        f"slowest {trees[0]['wall_s'] * 1e3:.3f} ms "
        f"({trees[0]['root_op']})",
        "",
    ]
    for t in trees[:top]:
        lines.append(
            f"-- {t['root_op']} trace={t['trace_id']:016x} "
            f"prio={t['priority']} wall={t['wall_s'] * 1e3:.3f} ms "
            f"spans={t['n_spans']} tracks={','.join(t['tracks'])} "
            f"attributed={t['attributed_frac'] * 100:.1f}%"
        )
        for op, secs in t["critical_path"]:
            lines.append(f"   critpath {op:<24} {secs * 1e3:9.3f} ms")
        lines.append("")
    hdr = (f"{'op':<16} {'prio':<6} {'phase':<24} {'n':>4} "
           f"{'p50_ms':>9} {'p99_ms':>9} {'share':>7}")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for r in phase_table(trees):
        lines.append(
            f"{r['op']:<16} {r['priority']:<6} {r['phase']:<24} "
            f"{r['n']:>4} {r['p50_s'] * 1e3:>9.3f} "
            f"{r['p99_s'] * 1e3:>9.3f} {r['share'] * 100:>6.1f}%"
        )
    return "\n".join(lines) + "\n"
